"""``distkeras-lint`` — the project-aware static-analysis suite
(ISSUE 12 + the ISSUE 14 concurrency-contract layer).

Three layers:

- the **tier-1 gate**: the full suite runs over THIS repo on every test
  run and must come back clean in under 10 seconds — lock-order,
  blocking-under-lock, guarded-by, wire-action parity, protocol model,
  telemetry registry, unused imports;
- **fixture tests**: each analyzer is proven against synthetic known-bad
  snippets (a seeded lock cycle, the PR-8 ``monitor()`` deadlock shape,
  an unguarded shared write, a lockset intersection going empty, a
  missing/extra protocol arm, a desyncing reply table, a misspelled
  ``ps_comit_bytes_total`` metric, a C++ hub missing a dispatch arm) and
  the suppression mechanisms are proven to suppress exactly the
  annotated line / allow-listed edge / declared attribute, never more;
- **dynamic cells** (slow-marked): the ``DKT_LOCKSET`` lockset stress
  harness and the ``-fsanitize=thread`` native hub stress, both of
  which must come back report-free at HEAD.
"""

import os
import subprocess
import time

import pytest

from distkeras_tpu.analysis import (blocking, cli, guarded_by, lock_manifest,
                                    lock_order, lockset, protocol_model,
                                    telemetry)
from distkeras_tpu.analysis import unused_imports as ui
from distkeras_tpu.analysis import wire_parity
from distkeras_tpu.analysis.core import Finding, SourceFile, repo_root
from distkeras_tpu.analysis.telemetry_registry import TELEMETRY_NAMES

ROOT = repo_root()


def _src(tmp_path, name, text):
    """Write a fixture module and return {path: SourceFile} for it."""
    p = tmp_path / name
    p.write_text(text)
    return {str(p): SourceFile(str(p), text)}


# -- the tier-1 gate -----------------------------------------------------------

def test_repo_is_lint_clean_under_budget():
    """THE gate: the full suite over the live tree — every finding fixed
    or allow-listed with a named reason — in under the 10 s budget."""
    t0 = time.perf_counter()
    results = cli.run_all(ROOT)
    elapsed = time.perf_counter() - t0
    flat = [str(f) for fs in results.values() for f in fs]
    assert not flat, "distkeras-lint findings:\n" + "\n".join(flat)
    assert set(results) == set(cli.PASSES)
    assert elapsed < 10.0, f"analysis gate took {elapsed:.1f}s (budget 10s)"


def test_cli_exits_zero_and_emits_json(capsys):
    """Pass selection + machine-readable report (a cheap subset — the
    full run is already covered by the gate above, and tier-1's wall
    budget is thin)."""
    import json

    rc = cli.main(["--root", ROOT, "--json", "--pass", "wire-parity",
                   "--pass", "telemetry"])
    out = capsys.readouterr().out
    assert rc == 0
    report = json.loads(out)
    assert report["total"] == 0
    assert set(report["findings"]) == {"wire-parity", "telemetry"}


def test_cli_console_script_is_registered():
    """CI/tooling satellite pin: the ``distkeras-lint`` entry point stays
    registered (and points at a callable that exists)."""
    with open(os.path.join(ROOT, "pyproject.toml")) as f:
        assert 'distkeras-lint = "distkeras_tpu.analysis.cli:main"' in f.read()
    assert callable(cli.main)


def test_cli_single_pass_selection(capsys):
    rc = cli.main(["--root", ROOT, "--pass", "wire-parity"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[wire-parity] clean" in out
    assert "[telemetry]" not in out


# -- lock-order fixtures -------------------------------------------------------

_CYCLE_FIXTURE = """\
import threading

class A:
    def __init__(self):
        self._l1 = threading.Lock()
        self._l2 = threading.Lock()

    def f(self):
        with self._l1:
            with self._l2:
                pass

    def g(self):
        with self._l2:
            with self._l1:
                pass
"""


def test_lock_order_detects_seeded_cycle(tmp_path):
    sources = _src(tmp_path, "cycle.py", _CYCLE_FIXTURE)
    findings = lock_order.check(sources, str(tmp_path),
                                order=["A._l1", "A._l2"], exceptions={})
    msgs = [f.message for f in findings]
    assert any("cycle" in m and "A._l1" in m and "A._l2" in m for m in msgs), msgs
    # the backward edge is also an order inversion against the manifest
    assert any("inverts the declared LOCK_ORDER" in m for m in msgs), msgs


def test_lock_order_detects_pr8_monitor_deadlock_shape(tmp_path):
    """The PR-8 bug reconstructed: ``monitor()`` takes the module default
    lock and calls ``collector()``, which takes the same non-reentrant
    lock — one level of call resolution sees the self-edge."""
    sources = _src(tmp_path, "health_fixture.py", """\
import threading

_default_lock = threading.Lock()
_collector = None

def collector():
    global _collector
    with _default_lock:
        if _collector is None:
            _collector = object()
        return _collector

def monitor():
    with _default_lock:
        c = collector()
        return c
""")
    findings = lock_order.check(sources, str(tmp_path), order=[],
                                exceptions={})
    assert any("re-acquisition of non-reentrant health_fixture._default_lock"
               in f.message and "call collector()" in f.message
               for f in findings), [f.message for f in findings]


def test_lock_order_cross_class_edge_via_annotation(tmp_path):
    """``self.hub`` typed via a constructor annotation resolves, so a
    feed-holds-into-hub nesting produces a (checkable) cross-class edge."""
    sources = _src(tmp_path, "feed.py", """\
import threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()

class Feed:
    def __init__(self, hub: "Hub"):
        self.hub = hub
        self._lock = threading.Lock()

    def attach(self):
        with self._lock:
            with self.hub._lock:
                pass
""")
    edges = lock_order.build_graph(sources, str(tmp_path))
    assert ("Feed._lock", "Hub._lock") in edges
    # declared backward -> inversion finding
    findings = lock_order.check(sources, str(tmp_path),
                                order=["Hub._lock", "Feed._lock"],
                                exceptions={})
    assert any("inverts" in f.message for f in findings)
    # declared forward -> clean
    assert not lock_order.check(sources, str(tmp_path),
                                order=["Feed._lock", "Hub._lock"],
                                exceptions={})


def test_lock_order_allowlist_suppresses_with_named_reason(tmp_path):
    sources = _src(tmp_path, "cycle.py", _CYCLE_FIXTURE)
    exceptions = {("A._l2", "A._l1"): "seeded fixture: g() is unreachable"}
    findings = lock_order.check(sources, str(tmp_path),
                                order=["A._l1", "A._l2"],
                                exceptions=exceptions)
    assert not findings, [f.message for f in findings]
    # an empty reason is itself a finding, never a silent suppression
    findings = lock_order.check(sources, str(tmp_path),
                                order=["A._l1", "A._l2"],
                                exceptions={("A._l2", "A._l1"): ""})
    assert any("no reason string" in f.message for f in findings)


def test_lock_order_resolves_callee_locks_in_their_own_module(tmp_path):
    """Cross-module call resolution must scope the callee's module-level
    locks to the module the callee is DEFINED in — resolving against the
    caller's module would miss the edge (or hit a same-named stranger)."""
    a = tmp_path / "hub_mod.py"
    a.write_text("""\
import threading

_mod_lock = threading.Lock()

class Hub:
    def poke(self):
        with _mod_lock:
            pass
""")
    b = tmp_path / "feed_mod.py"
    b.write_text("""\
import threading

class Feed:
    def __init__(self, hub: "Hub"):
        self.hub = hub
        self._lock = threading.Lock()

    def attach(self):
        with self._lock:
            self.hub.poke()
""")
    sources = {str(p): SourceFile(str(p)) for p in (a, b)}
    edges = lock_order.build_graph(sources, str(tmp_path))
    assert ("Feed._lock", "hub_mod._mod_lock") in edges, sorted(edges)


def test_lock_order_default_manifest_catches_center_lock_self_deadlock(
        tmp_path):
    """The shipped manifest must NOT pre-suppress a PR-8-shape
    re-acquisition of the center lock (a dead allow-list entry would
    mask the exact bug class the pass exists to catch)."""
    sources = _src(tmp_path, "hub.py", """\
import threading

class SocketParameterServer:
    def __init__(self):
        self._lock = threading.Lock()

    def get_weights(self):
        with self._lock:
            return 1

    def monitor(self):
        with self._lock:
            return self.get_weights()
""")
    findings = lock_order.check(sources, str(tmp_path))  # REAL manifest
    assert any("re-acquisition of non-reentrant SocketParameterServer._lock"
               in f.message for f in findings), [f.message for f in findings]


def test_lock_order_callee_summary_excludes_deferred_code(tmp_path):
    """A lock acquired inside a lambda (or nested def) a callee merely
    BUILDS is deferred — it must not become an acquisition edge for a
    caller holding another lock."""
    sources = _src(tmp_path, "m.py", """\
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.pool = None

    def kick(self):
        self.pool.submit(lambda: self._b.acquire())

    def f(self):
        with self._a:
            self.kick()
""")
    edges = lock_order.build_graph(sources, str(tmp_path))
    assert ("C._a", "C._b") not in edges, sorted(edges)


def test_lock_order_sees_match_case_arms(tmp_path):
    sources = _src(tmp_path, "m.py", """\
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def f(self, msg):
        with self._a:
            match msg:
                case 1:
                    with self._b:
                        pass
                case _:
                    pass
""")
    edges = lock_order.build_graph(sources, str(tmp_path))
    assert ("C._a", "C._b") in edges, sorted(edges)


def test_lock_order_reports_stale_exception_entries(tmp_path):
    """The manifest is self-cleaning: an EXCEPTIONS entry whose edge no
    longer exists in the graph would pre-suppress a future genuine
    finding on that pair, so it is itself a finding."""
    sources = _src(tmp_path, "m.py", """\
import threading

class A:
    def __init__(self):
        self._l1 = threading.Lock()
""")
    findings = lock_order.check(
        sources, str(tmp_path), order=["A._l1", "A._l2"],
        exceptions={("A._l2", "A._l1"): "edge refactored away long ago"})
    assert any("stale exception" in f.message for f in findings), \
        [f.message for f in findings]


def test_blocking_annotation_on_multiline_call_last_line(tmp_path):
    """A multi-line call's annotation naturally lands on the closing
    line; suppression must match anywhere in the statement's span (and
    must NOT then double-report as stale)."""
    sources = _src(tmp_path, "blk.py", """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.sock = None

    def f(self):
        with self._lock:
            self.sock.sendall(
                b"x")  # lint: blocking-ok fixture: bounded by test design
""")
    assert not blocking.check(sources, str(tmp_path), io_locks={})


def test_telemetry_flags_unknown_annotation_rule(tmp_path):
    """A typo'd or unowned rule id in an annotation is inert — never
    honored, so it must be reported instead of accumulating."""
    sources = _src(tmp_path, "mod.py", """\
X = 1  # lint: telemtry-ok misspelled rule, would silently do nothing
""")
    findings = telemetry.check(sources, {}, str(tmp_path))
    assert len(findings) == 1
    assert "unknown lint rule 'telemtry'" in findings[0].message


def test_lock_order_requires_manifest_membership(tmp_path):
    sources = _src(tmp_path, "feed.py", """\
import threading

class B:
    def __init__(self):
        self._x = threading.Lock()
        self._y = threading.Lock()

    def f(self):
        with self._x:
            with self._y:
                pass
""")
    findings = lock_order.check(sources, str(tmp_path), order=[],
                                exceptions={})
    assert any("not declared in lock_manifest.LOCK_ORDER" in f.message
               for f in findings)


def test_lock_graph_still_sees_the_real_nestings():
    """Meta-regression: a 'clean' verdict is only meaningful while the
    analyzer can SEE the tree's real acquisition edges.  Pin the four
    known nestings of the hub stack — if a refactor makes them invisible
    (or removes them), this fails and the manifest gets revisited."""
    from distkeras_tpu.analysis.core import load_sources, python_files

    sources = load_sources(python_files(ROOT, lock_order.DEFAULT_SUBDIRS))
    edges = lock_order.build_graph(sources, ROOT)
    expected = {
        ("ReplicationFeed._lock", "SocketParameterServer._lock"),
        ("ReplicationFeed._lock", "SocketParameterServer._conn_lock"),
        ("_AdaptiveCombiner._drain", "_AdaptiveCombiner._qlock"),
        ("_AdaptiveCombiner._drain", "SocketParameterServer._lock"),
    }
    assert expected <= set(edges), sorted(edges)


# -- blocking-under-lock fixtures ----------------------------------------------

_BLOCKING_FIXTURE = """\
import threading
import time

class C:
    def __init__(self):
        self._state_lock = threading.Lock()
        self.sock = None

    def f(self):
        with self._state_lock:
            self.sock.sendall(b"x")
            self.sock.sendall(b"y")  # lint: blocking-ok fixture: bounded by test timeout
            time.sleep(1)
"""


def test_blocking_detects_and_annotation_suppresses_exactly_one(tmp_path):
    sources = _src(tmp_path, "blk.py", _BLOCKING_FIXTURE)
    findings = blocking.check(sources, str(tmp_path), io_locks={})
    lines = sorted(f.line for f in findings)
    assert lines == [11, 13], [str(f) for f in findings]  # not line 12


def test_blocking_annotation_without_reason_is_a_finding(tmp_path):
    sources = _src(tmp_path, "blk.py", """\
import threading

class C:
    def __init__(self):
        self._state_lock = threading.Lock()
        self.sock = None

    def f(self):
        with self._state_lock:
            self.sock.sendall(b"x")  # lint: blocking-ok
""")
    findings = blocking.check(sources, str(tmp_path), io_locks={})
    assert len(findings) == 1
    assert "requires a reason" in findings[0].message


def test_blocking_io_lock_declaration_suppresses_whole_lock(tmp_path):
    # annotation-free variant: under an IO_LOCKS declaration no findings
    # fire, so a line annotation would (correctly) read as stale
    sources = _src(tmp_path, "blk.py", """\
import threading
import time

class C:
    def __init__(self):
        self._state_lock = threading.Lock()
        self.sock = None

    def f(self):
        with self._state_lock:
            self.sock.sendall(b"x")
            time.sleep(1)
""")
    findings = blocking.check(
        sources, str(tmp_path),
        io_locks={"C._state_lock": "fixture: this lock serializes I/O"})
    assert not findings
    # ...but an empty reason on the declaration is a finding
    findings = blocking.check(sources, str(tmp_path),
                              io_locks={"C._state_lock": " "})
    assert any("no reason string" in f.message for f in findings)


def test_blocking_flags_pr7_shapes_not_str_join(tmp_path):
    sources = _src(tmp_path, "blk.py", """\
import subprocess
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.fut = None
        self.thread = None

    def f(self):
        with self._lock:
            self.fut.result()
            self.thread.join()
            self.thread.join(timeout=5)
            subprocess.run(["true"])
            x = ",".join(["a", "b"])
            return x
""")
    findings = blocking.check(sources, str(tmp_path), io_locks={})
    lines = sorted(f.line for f in findings)
    assert lines == [12, 13, 14, 15], [str(f) for f in findings]


def test_blocking_reports_stale_and_reasonless_annotations(tmp_path):
    """Suppressions are self-cleaning: a reasonless annotation is a
    finding even with no co-located violation, and a reasoned annotation
    whose violation was refactored away is reported as stale."""
    sources = _src(tmp_path, "blk.py", """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self):
        n = 1  # lint: blocking-ok
        m = 2  # lint: blocking-ok the call this excused is long gone
        return n + m
""")
    findings = blocking.check(sources, str(tmp_path), io_locks={})
    msgs = sorted((f.line, f.message) for f in findings)
    assert len(msgs) == 2, msgs
    assert "requires a reason" in msgs[0][1] and msgs[0][0] == 8
    assert "stale suppression" in msgs[1][1] and msgs[1][0] == 9


def test_blocking_flags_with_item_context_expressions(tmp_path):
    """A blocking call used AS a context manager under a held lock
    (``with lock: with sock.accept() as c:``) is still under the lock
    while it blocks — the with-item position must not hide it."""
    sources = _src(tmp_path, "blk.py", """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.sock = None

    def f(self):
        with self._lock:
            with self.sock.accept() as conn:
                return conn
""")
    findings = blocking.check(sources, str(tmp_path), io_locks={})
    assert [f.line for f in findings] == [10], [str(f) for f in findings]


def test_blocking_ignores_lambda_bodies(tmp_path):
    """A lambda BUILT under a lock runs later, outside it — calls inside
    its body are neither blocking-under-lock nor lock acquisitions."""
    sources = _src(tmp_path, "blk.py", """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.sock = None
        self.cb = None

    def f(self):
        with self._lock:
            self.cb = lambda: self.sock.recv(4)
""")
    assert not blocking.check(sources, str(tmp_path), io_locks={})


def test_blocking_outside_lock_region_is_clean(tmp_path):
    sources = _src(tmp_path, "blk.py", """\
import threading
import time

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.sock = None

    def f(self):
        with self._lock:
            n = 1
        self.sock.sendall(b"x")
        time.sleep(0)
        return n
""")
    assert not blocking.check(sources, str(tmp_path), io_locks={})


def test_replication_feed_send_sites_stay_annotated():
    """Regression pin for the real blocking findings in the hub paths:
    the two ReplicationFeed sends run under the feed lock BY DESIGN
    (send-before-ack, stall bounded by REPLICA_SEND_TIMEOUT) and carry
    line annotations with reasons.  If the annotations are dropped, the
    gate fails; if the sends move, this pin makes the change explicit."""
    path = os.path.join(ROOT, "distkeras_tpu", "runtime",
                        "parameter_server.py")
    src = SourceFile(path)
    feed_anns = [(line, rule, reason)
                 for line, (rule, reason) in sorted(src.annotations.items())
                 if rule == "blocking"]
    assert len(feed_anns) >= 2, feed_anns
    assert all(reason.strip() for _, _, reason in feed_anns), feed_anns


# -- wire-action parity fixtures -----------------------------------------------

_NET_FIXTURE = """\
ACTION_PULL = b"P"
ACTION_ZAP = b"Z"
"""

_PS_FIXTURE = """\
class Hub:
    def _handle_connection(self, conn):
        action = self._read(conn)
        if action == net.ACTION_PULL:
            pass
        elif action == net.ACTION_ZAP:
            pass
"""


def _parity(tmp_path, cpp_text):
    net_src = SourceFile(str(tmp_path / "networking.py"), _NET_FIXTURE)
    ps_src = SourceFile(str(tmp_path / "parameter_server.py"), _PS_FIXTURE)
    return wire_parity.check_parity(net_src, ps_src,
                                    str(tmp_path / "hub.cpp"), cpp_text,
                                    str(tmp_path))


def test_wire_parity_detects_missing_cpp_dispatch_arm(tmp_path):
    findings = _parity(tmp_path, """\
      if (action == 'P') { serve(); }
      else { close(); }
""")
    assert any("'Z'" in f.message and "neither handled nor explicitly "
               "refused" in f.message for f in findings), \
        [f.message for f in findings]


def test_wire_parity_clean_when_handled_or_refused(tmp_path):
    assert not _parity(tmp_path, """\
      if (action == 'P') { serve(); }
      else if (action == 'Z') { zap(); }
""")
    # an explicit refusal comment naming the byte also satisfies parity
    assert not _parity(tmp_path, """\
      // 'Z' refused: python-hub-only (sparse inproc pair)
      if (action == 'P') { serve(); }
""")


def test_wire_parity_detects_unregistered_cpp_byte(tmp_path):
    findings = _parity(tmp_path, """\
      if (action == 'P') { serve(); }
      else if (action == 'Z') { zap(); }
      else if (action == 'K') { kaboom(); }
""")
    assert any("'K'" in f.message and "not a registered ACTION_" in f.message
               for f in findings)


def test_wire_parity_real_registry_is_complete():
    """Pin the real contract: every registered action byte appears in
    ``native/ps_server.cpp``, and the registry is the full 16-action
    protocol (a new action that skips the registry or the native story
    fails the gate, not a reviewer's memory)."""
    net_src = SourceFile(os.path.join(ROOT, "distkeras_tpu", "runtime",
                                      "networking.py"))
    registry = wire_parity.parse_action_registry(net_src)
    assert len(registry) >= 16, sorted(registry)
    with open(os.path.join(ROOT, "native", "ps_server.cpp")) as f:
        _, referenced = wire_parity.cpp_action_bytes(f.read())
    missing = {n: b for n, (b, _) in registry.items() if b not in referenced}
    assert not missing, missing


def test_nie_knob_staleness_detected_and_real_messages_clean(tmp_path):
    sources = _src(tmp_path, "mod.py", """\
def serve(transport="socket"):
    raise NotImplementedError(
        "frob is unported: use frobnicate=True or transport='socket'")
""")
    findings = wire_parity.check_nie_knobs(sources, str(tmp_path))
    assert any("'frobnicate='" in f.message for f in findings)
    assert not any("'transport='" in f.message for f in findings)
    # and the real tree's guidance names only knobs that exist
    from distkeras_tpu.analysis.core import load_sources, python_files

    real = load_sources(python_files(ROOT, ("distkeras_tpu",),
                                     extra=("bench.py",)))
    assert not wire_parity.check_nie_knobs(real, ROOT)


# -- telemetry registry fixtures -----------------------------------------------

def test_telemetry_detects_misspelled_metric(tmp_path):
    sources = _src(tmp_path, "mod.py", """\
from distkeras_tpu import observability as obs

def f(n):
    obs.counter("ps_comit_bytes_total").inc(n)
""")
    findings = telemetry.check(sources, {}, str(tmp_path))
    assert len(findings) == 1
    assert "ps_comit_bytes_total" in findings[0].message
    # the corrected name is registered -> clean
    sources = _src(tmp_path, "mod2.py", """\
from distkeras_tpu import observability as obs

def f(n):
    obs.counter("ps_commit_bytes_total").inc(n)
""")
    assert not telemetry.check(sources, {}, str(tmp_path))


def test_telemetry_sweeps_namespace_literals_and_cpp(tmp_path):
    sources = _src(tmp_path, "mod.py", """\
NAMES = {"ps.sparse_rows_comitted": 1}
""")
    findings = telemetry.check(sources, {}, str(tmp_path))
    assert len(findings) == 1 and "ps.sparse_rows_comitted" in findings[0].message
    cpp = {str(tmp_path / "hub.cpp"):
           'const char* kName = "ps_comit_bytes_total";\n'}
    findings = telemetry.check({}, cpp, str(tmp_path))
    assert len(findings) == 1 and "C++ literal" in findings[0].message


def test_telemetry_annotation_suppresses_with_reason(tmp_path):
    sources = _src(tmp_path, "mod.py", """\
BAD = "ps.not_a_real_series"  # lint: telemetry-ok fixture constant, never emitted
""")
    assert not telemetry.check(sources, {}, str(tmp_path))


def test_telemetry_registry_has_no_orphan_shape():
    """Every registry entry is itself namespace- or metric-shaped (a
    malformed entry could never match a literal and would silently
    grandfather typos)."""
    import re

    shape = re.compile(r"^[a-z][a-z0-9_.]+$")
    bad = [n for n in TELEMETRY_NAMES if not shape.match(n)]
    assert not bad, bad


# -- unused-import pass --------------------------------------------------------

def test_unused_import_pass_detects_and_honors_noqa(tmp_path):
    p = tmp_path / "m.py"
    p.write_text("import os\nimport sys  # noqa: F401\n\nprint(1)\n")
    findings = ui.check_files([str(p)], str(tmp_path))
    assert [f.line for f in findings] == [1]
    assert "'os'" in findings[0].message


def test_unused_import_packages_cover_the_historical_cells():
    """The consolidated pass must scan at least every tree the old
    per-package test cells scanned (plus the analysis package itself)."""
    assert {"observability", "runtime", ".", "tests", "data", "parallel",
            "models", "ops", "examples", "bench",
            "analysis"} <= set(ui.PACKAGES)


# -- optional C++ linters (present-in-container only) --------------------------

@pytest.mark.parametrize("tool,args", [
    ("cppcheck", ["--std=c++17", "--language=c++", "--error-exitcode=2",
                  "--enable=warning,portability",
                  "--suppress=missingIncludeSystem"]),
    ("clang-tidy", ["--warnings-as-errors=*", "--quiet"]),
])
def test_native_cpp_static_analysis(tool, args):
    """CI/tooling satellite: run clang-tidy/cppcheck over ``native/*.cpp``
    when the container ships them (skip-guarded via the shared
    ``require_tool`` helper, like the ``-Werror`` and TSAN cells)."""
    from conftest import require_tool

    require_tool(tool)
    srcs = sorted(
        os.path.join(ROOT, "native", f)
        for f in os.listdir(os.path.join(ROOT, "native"))
        if f.endswith(".cpp"))
    assert srcs
    if tool == "clang-tidy":
        cmd = [tool] + srcs + args + ["--", "-std=c++17"]
    else:
        cmd = [tool] + args + srcs
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- guarded-by fixtures (ISSUE 14 tentpole) -----------------------------------

_SHARED_FIXTURE = """\
import threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            self._count += 1

    def bump(self):
        self._count += 1
"""


def test_guarded_by_detects_undeclared_shared_write(tmp_path):
    """An attribute written from a thread root AND the caller's thread
    with no GUARDED_BY entry flags at every write site (outside
    ``__init__``)."""
    sources = _src(tmp_path, "hub.py", _SHARED_FIXTURE)
    findings = guarded_by.check(sources, str(tmp_path), guarded_by={})
    lines = sorted(f.line for f in findings)
    assert lines == [13, 16], [str(f) for f in findings]
    assert all("no GUARDED_BY entry" in f.message for f in findings)
    assert any("Hub._loop" in f.message for f in findings)


def test_guarded_by_declared_guard_checks_held_region(tmp_path):
    sources = _src(tmp_path, "hub.py", """\
import threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            with self._lock:
                self._count += 1

    def bump(self):
        self._count += 1
""")
    table = {"Hub._count": ("Hub._lock", "")}
    findings = guarded_by.check(sources, str(tmp_path), guarded_by=table)
    assert [f.line for f in findings] == [17], [str(f) for f in findings]
    assert "outside its held region" in findings[0].message
    assert "Hub._lock" in findings[0].message


def test_guarded_by_annotation_suppresses_exactly_one_line(tmp_path):
    sources = _src(tmp_path, "hub.py", """\
import threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            self._count += 1  # lint: unguarded-ok fixture: loop owns it pre-promotion
            self._count += 2

    def bump(self):
        with self._lock:
            self._count += 1
""")
    table = {"Hub._count": ("Hub._lock", "")}
    findings = guarded_by.check(sources, str(tmp_path), guarded_by=table)
    assert [f.line for f in findings] == [14], [str(f) for f in findings]


def test_guarded_by_entry_held_inference_covers_locked_helpers(tmp_path):
    """The ``*_locked`` convention, checked instead of trusted: a helper
    whose EVERY resolved call site holds the guard is lock-held at
    entry, so its writes are clean — and a second caller without the
    lock breaks the inference."""
    clean = """\
import threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()
        self._clock = 0

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            self.commit()

    def commit(self):
        with self._lock:
            self._apply_locked()

    def _apply_locked(self):
        self._clock += 1
"""
    table = {"Hub._clock": ("Hub._lock", "")}
    sources = _src(tmp_path, "hub.py", clean)
    assert not guarded_by.check(sources, str(tmp_path), guarded_by=table)
    broken = clean + """\

    def sneak(self):
        self._apply_locked()
"""
    sources = _src(tmp_path, "hub2.py", broken)
    findings = guarded_by.check(sources, str(tmp_path), guarded_by=table)
    assert [f.line for f in findings] == [20], [str(f) for f in findings]


def test_guarded_by_multi_root_handler_loop_is_shared(tmp_path):
    """A root spawned in a loop (one handler thread per connection)
    races ITSELF — attributes it writes are shared even with no other
    writer."""
    sources = _src(tmp_path, "hub.py", """\
import threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()
        self._served = 0

    def _accept_loop(self):
        while True:
            threading.Thread(target=self._handle, daemon=True).start()

    def start(self):
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _handle(self):
        self._served += 1
""")
    findings = guarded_by.check(sources, str(tmp_path), guarded_by={})
    assert [f.line for f in findings] == [16], [str(f) for f in findings]


def test_guarded_by_element_store_counts_and_init_exempt(tmp_path):
    sources = _src(tmp_path, "hub.py", """\
import threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()
        self._center = [0, 0]
        self._center[0] = 1  # __init__ writes are exempt

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            self._center[0] += 1

    def reset(self):
        self._center[1] = 0
""")
    findings = guarded_by.check(sources, str(tmp_path), guarded_by={})
    assert sorted(f.line for f in findings) == [14, 17], \
        [str(f) for f in findings]


def test_guarded_by_manifest_is_self_cleaning(tmp_path):
    """Stale entries, unknown guards, and reasonless None guards are
    findings; a reasoned None entry suppresses whole-attribute."""
    sources = _src(tmp_path, "hub.py", _SHARED_FIXTURE)
    # stale: attr not shared anywhere
    findings = guarded_by.check(
        sources, str(tmp_path),
        guarded_by={"Hub._gone": ("Hub._lock", ""),
                    "Hub._count": ("Hub._lock", "")})
    msgs = [f.message for f in findings]
    assert any("stale GUARDED_BY entry" in m and "Hub._gone" in m
               for m in msgs), msgs
    # unknown guard lock node
    findings = guarded_by.check(
        sources, str(tmp_path),
        guarded_by={"Hub._count": ("Hub._mystery_lock", "")})
    assert any("not a known lock node" in f.message for f in findings)
    # None guard requires a reason...
    findings = guarded_by.check(
        sources, str(tmp_path), guarded_by={"Hub._count": (None, " ")})
    assert any("no reason" in f.message for f in findings)
    # ...and with one, the attribute is by-design unguarded: clean
    assert not guarded_by.check(
        sources, str(tmp_path),
        guarded_by={"Hub._count": (None, "fixture: monotonic hint only")})


def test_guarded_by_subscribe_callback_is_a_root(tmp_path):
    sources = _src(tmp_path, "hub.py", """\
import threading

class Hub:
    def __init__(self, monitor):
        self._lock = threading.Lock()
        self._scale = 1.0
        self.monitor = monitor

    def start(self):
        self.monitor.subscribe(self._on_event)

    def _on_event(self, event):
        self._scale = 0.5

    def reset(self):
        self._scale = 1.0
""")
    findings = guarded_by.check(sources, str(tmp_path), guarded_by={})
    assert sorted(f.line for f in findings) == [13, 16], \
        [str(f) for f in findings]
    assert any("Hub._on_event" in f.message for f in findings)


def test_guarded_by_real_tree_discovery_pins():
    """Meta-regression: the pass only means something while it can SEE
    the hub's real thread roots and shared state.  Pin the handler loop
    as a multi root, the clock under the center lock, and the
    by-design ``_consume_one_inner`` annotations."""
    from distkeras_tpu.analysis.core import load_sources, python_files

    sources = load_sources(python_files(ROOT, lock_order.DEFAULT_SUBDIRS))
    gb = guarded_by.GuardedByIndex(sources, ROOT)
    assert gb.roots.get("SocketParameterServer._handle_connection") is True
    assert "SocketParameterServer._replica_loop" in gb.roots
    assert "PSClient._heartbeat_loop" in gb.roots
    shared = gb.shared_attrs(gb.contexts())
    assert "SocketParameterServer._clock" in shared
    assert lock_manifest.GUARDED_BY["SocketParameterServer._clock"][0] == \
        "SocketParameterServer._lock"
    # the three receive-leg timestamp stores stay annotated WITH reasons
    ps = SourceFile(os.path.join(ROOT, "distkeras_tpu", "runtime",
                                 "parameter_server.py"))
    anns = [(ln, reason) for ln, (rule, reason) in ps.annotations.items()
            if rule == "unguarded"]
    assert len(anns) >= 3, anns
    assert all(reason.strip() for _, reason in anns), anns


# -- protocol-model fixtures ---------------------------------------------------

_PM_NET = """\
ACTION_PULL = b"P"
ACTION_WEIGHTS = b"W"
ACTION_ZAP = b"Z"
"""

_PM_PS = """\
class Hub:
    def _handle_connection(self, conn):
        action = self._read(conn)
        if action == net.ACTION_PULL:
            reply.pack(net.ACTION_WEIGHTS)
"""


def test_protocol_modeled_but_unhandled_arm(tmp_path):
    net_src = SourceFile(str(tmp_path / "networking.py"), _PM_NET)
    ps_src = SourceFile(str(tmp_path / "parameter_server.py"), _PM_PS)
    findings = protocol_model.check_model_vs_dispatch(
        net_src, ps_src, str(tmp_path),
        requests={"ACTION_PULL": "ACTION_WEIGHTS", "ACTION_ZAP": None})
    assert any("modeled-but-unhandled" in f.message and "ACTION_ZAP"
               in f.message for f in findings), [f.message for f in findings]


def test_protocol_admitted_but_unmodeled_arm(tmp_path):
    net_src = SourceFile(str(tmp_path / "networking.py"), _PM_NET)
    ps_src = SourceFile(str(tmp_path / "parameter_server.py"), """\
class Hub:
    def _handle_connection(self, conn):
        action = self._read(conn)
        if action == net.ACTION_PULL:
            reply.pack(net.ACTION_WEIGHTS)
        elif action == net.ACTION_ZAP:
            pass
""")
    findings = protocol_model.check_model_vs_dispatch(
        net_src, ps_src, str(tmp_path),
        requests={"ACTION_PULL": "ACTION_WEIGHTS"})
    assert any("admitted-but-unmodeled" in f.message and "ACTION_ZAP"
               in f.message for f in findings), [f.message for f in findings]


def test_protocol_modeled_but_unproduced_reply(tmp_path):
    net_src = SourceFile(str(tmp_path / "networking.py"), _PM_NET)
    ps_src = SourceFile(str(tmp_path / "parameter_server.py"), """\
class Hub:
    def _handle_connection(self, conn):
        action = self._read(conn)
        if action == net.ACTION_PULL:
            pass
""")
    findings = protocol_model.check_model_vs_dispatch(
        net_src, ps_src, str(tmp_path),
        requests={"ACTION_PULL": "ACTION_WEIGHTS"})
    assert any("modeled-but-unproduced" in f.message for f in findings), \
        [f.message for f in findings]


def test_protocol_session_exploration_finds_desync_and_deadlock():
    """Bounded exhaustive 2-client interleavings: a hub replying the
    wrong kind desyncs; a hub missing an arm deadlocks; the shipped
    table does neither."""
    assert not protocol_model.explore_sessions()
    skew = dict(protocol_model.REQUESTS)
    skew["ACTION_PULL"] = "ACTION_ACK"
    findings = protocol_model.explore_sessions(hub_replies=skew)
    assert findings and all("desync" in f.message for f in findings)
    missing = dict(protocol_model.REQUESTS)
    del missing["ACTION_COMMIT"]
    findings = protocol_model.explore_sessions(hub_replies=missing)
    assert any("deadlock" in f.message for f in findings)


def test_protocol_standby_model_checks_promotion():
    """The standby machine: shipped rules promote and never ack while
    standby; breaking commit-promotion produces acked-while-standby,
    and breaking every promotion path makes promotion unreachable."""
    assert not protocol_model.explore_standby()
    rules = dict(protocol_model.STANDBY_RULES)
    rules["commit_promotes"] = False
    findings = protocol_model.explore_standby(rules=rules)
    assert any("acked-commit-while-standby" in f.message for f in findings)
    rules["loss_exhaustion_promotes"] = False
    findings = protocol_model.explore_standby(rules=rules)
    assert any("unreachable-promotion" in f.message for f in findings)


def test_protocol_shm_attach_model_checks_handshake():
    """The shm attach machine (ISSUE 18): the shipped rules settle every
    hub generation untorn, and flipping each safety rule produces its
    named failure — stranded replies, torn attaches, dead ring peers."""
    assert not protocol_model.explore_shm()
    for rule, needle in (
            ("reply_before_switch", "stranded-reply"),
            ("switch_requires_confirm", "torn-attach"),
            ("decline_keeps_tcp", "torn-attach"),
            ("abort_keeps_tcp", "torn-attach"),
            ("legacy_close_is_decline", "torn-attach"),
            ("sever_wakes_ring_peer", "dead-ring-peer")):
        rules = dict(protocol_model.SHM_RULES)
        rules[rule] = False
        findings = protocol_model.explore_shm(rules=rules)
        assert any(needle in f.message for f in findings), \
            f"flipping {rule} produced no {needle} finding"


def test_protocol_fleet_model_checks_join_drain_admission():
    """The fleet join/drain/admission machine (ISSUE 19): the shipped
    rules settle every interleaving clean, and flipping each safety rule
    produces its named failure — a rejected job observing state, an
    acked commit lost across a drain, a respawn committing blind, a
    retire racing its drain."""
    assert not protocol_model.explore_fleet()
    for rule, needle in (
            ("admission_before_attach", "admission-races-attach"),
            ("reject_never_serves", "post-reject-served"),
            ("drain_completes_inflight", "acked-commit-loss"),
            ("respawn_pulls_current_center", "respawn-blind-commit"),
            ("retire_after_drain_only", "retire-before-drain")):
        rules = dict(protocol_model.FLEET_RULES)
        rules[rule] = False
        findings = protocol_model.explore_fleet(rules=rules)
        assert any(needle in f.message for f in findings), \
            f"flipping {rule} produced no {needle} finding"


def test_protocol_model_covers_full_registry():
    """Every registered ACTION_* byte is either a modeled request or a
    modeled reply — a 17th action must extend the model in the same PR
    that registers it."""
    net_src = SourceFile(os.path.join(ROOT, "distkeras_tpu", "runtime",
                                      "networking.py"))
    registry = wire_parity.parse_action_registry(net_src)
    modeled = set(protocol_model.REQUESTS) | {
        r for r in protocol_model.REQUESTS.values() if r}
    assert set(registry) == modeled, sorted(
        set(registry) ^ modeled)


# -- lockset (dynamic) fixtures ------------------------------------------------

def test_lockset_declared_guard_violation_detected():
    import threading

    class Victim:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0

        def bump_racy(self):
            self._count += 1

    with lockset.instrument(
            Victim,
            guarded_by={"Victim._count": ("Victim._lock", "")}) as chk:
        v = Victim()
        ts = [threading.Thread(
            target=lambda: [v.bump_racy() for _ in range(50)])
            for _ in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert any("declared guarded by Victim._lock" in f.message
               for f in chk.findings), [str(f) for f in chk.findings]
    assert all(f.rule == "lockset" for f in chk.findings)


def test_lockset_empty_intersection_on_undeclared_attr():
    import threading

    class Victim:
        def __init__(self):
            self._l1 = threading.Lock()
            self._l2 = threading.Lock()
            self._x = 0

        def a(self):
            with self._l1:
                self._x += 1

        def b(self):
            with self._l2:
                self._x += 1

    with lockset.instrument(Victim) as chk:
        v = Victim()
        ts = [threading.Thread(target=lambda fn=fn: [fn() for _ in range(50)])
              for fn in (v.a, v.b, v.a)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert any("lockset went EMPTY" in f.message for f in chk.findings), \
        [str(f) for f in chk.findings]


def test_lockset_consistent_locking_and_handoff_are_clean():
    """One consistent guard never flags; init-then-handoff to a single
    other thread (daemon-loop state) never flags either — the classic
    Eraser false positive the two-writer refinement removes."""
    import threading

    class Clean:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            self._owned = 0  # written only by the loop thread after init

        def bump(self):
            with self._lock:
                self._n += 1

        def loop(self):
            for _ in range(100):
                self._owned += 1

    with lockset.instrument(Clean) as chk:
        c = Clean()
        ts = [threading.Thread(target=lambda: [c.bump() for _ in range(50)])
              for _ in range(2)]
        ts.append(threading.Thread(target=c.loop))
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert not chk.findings, [str(f) for f in chk.findings]
    assert chk.writes_checked > 0


def test_lockset_instrument_restores_classes():
    import threading

    class Plain:
        def __init__(self):
            self._lock = threading.Lock()
            self._x = 0

    before_setattr = Plain.__dict__.get("__setattr__")
    before_init = Plain.__init__
    with lockset.instrument(Plain):
        p = Plain()
        assert isinstance(p._lock, lockset.TrackingLock)
    assert Plain.__dict__.get("__setattr__") is before_setattr
    assert Plain.__init__ is before_init
    assert isinstance(Plain()._lock, type(threading.Lock()))


def test_lockset_run_is_inert_without_env(monkeypatch):
    monkeypatch.delenv("DKT_LOCKSET", raising=False)
    assert lockset.run(ROOT) == []
    assert not lockset.enabled()
    monkeypatch.setenv("DKT_LOCKSET", "1")
    assert lockset.enabled()


@pytest.mark.slow
def test_lockset_stress_harness_is_clean():
    """The DKT_LOCKSET gate: hammer commit/pull/sparse/replication/health
    concurrently under instrumentation — zero dynamic findings at HEAD
    (the guarded-by table holds at runtime, not just lexically)."""
    findings = lockset.stress(duration=2.0)
    assert not findings, [str(f) for f in findings]


# -- baseline mode (incremental adoption) --------------------------------------

def _fake_results():
    return {"guarded-by": [
        Finding("unguarded", "pkg/a.py", 10, "A is unguarded"),
        Finding("unguarded", "pkg/b.py", 20, "B is unguarded"),
    ]}


def test_baseline_write_compare_and_burn_down(tmp_path):
    base = tmp_path / "lint-baseline.json"
    n = cli.write_baseline(str(base), _fake_results())
    assert n == 2
    loaded = cli.load_baseline(str(base))
    # identical findings: all suppressed, nothing stale, nothing new
    kept, suppressed, stale = cli.apply_baseline(_fake_results(), loaded)
    assert suppressed == 2 and not stale
    assert not any(kept.values())
    # one fixed, one new: the fixed entry reports stale, the new fails
    now = {"guarded-by": [
        Finding("unguarded", "pkg/b.py", 21, "B is unguarded"),  # line moved
        Finding("unguarded", "pkg/c.py", 5, "C is unguarded"),   # new
    ]}
    kept, suppressed, stale = cli.apply_baseline(now, loaded)
    assert suppressed == 1  # B matches despite the line shift
    assert [s[1] for s in stale] == ["pkg/a.py"]
    assert [f.path for f in kept["guarded-by"]] == ["pkg/c.py"]


def test_baseline_is_multiplicity_aware_and_pass_subset_safe(tmp_path):
    """A baseline with ONE entry suppresses at most one identical
    finding — a second same-message violation (a new unguarded write of
    the same attribute) still fails — and a --pass subset run must not
    report other passes' entries as stale."""
    base = tmp_path / "base.json"
    cli.write_baseline(str(base), _fake_results())
    loaded = cli.load_baseline(str(base))
    doubled = {"guarded-by": [
        Finding("unguarded", "pkg/a.py", 10, "A is unguarded"),
        Finding("unguarded", "pkg/a.py", 30, "A is unguarded"),  # NEW site
        Finding("unguarded", "pkg/b.py", 20, "B is unguarded"),
    ]}
    kept, suppressed, stale = cli.apply_baseline(doubled, loaded)
    assert suppressed == 2 and not stale
    assert [f.line for f in kept["guarded-by"]] == [30]
    # subset run: only the lock-order pass executed, so the guarded-by
    # entries are NOT stale (their pass never looked)
    kept, suppressed, stale = cli.apply_baseline({"lock-order": []}, loaded)
    assert suppressed == 0 and not stale


def test_baseline_inert_lockset_entries_never_read_stale(tmp_path,
                                                         monkeypatch):
    """A lockset baseline entry (recorded under DKT_LOCKSET=1) must not
    be reported stale by a plain run, where the lockset pass 'ran' but
    checked nothing — and must be once the checker is live again."""
    loaded = [("lockset", "pkg/hub.py", "X raced")]
    monkeypatch.delenv("DKT_LOCKSET", raising=False)
    _kept, _sup, stale = cli.apply_baseline({"lockset": []}, loaded)
    assert not stale
    monkeypatch.setenv("DKT_LOCKSET", "1")
    _kept, _sup, stale = cli.apply_baseline({"lockset": []}, loaded)
    assert stale == loaded


def test_stray_lockset_annotation_is_flagged_as_unknown_rule(tmp_path):
    """The dynamic lockset pass deliberately has NO annotation rule —
    a '# lint: lockset-ok' comment is inert, so the hygiene sweep must
    report it instead of letting it accumulate."""
    sources = _src(tmp_path, "mod.py",
                   "X = 1  # lint: lockset-ok would be silently inert\n")
    findings = telemetry.check(sources, {}, str(tmp_path))
    assert len(findings) == 1
    assert "unknown lint rule 'lockset'" in findings[0].message


def test_baseline_cli_round_trip(tmp_path, capsys):
    """e2e: --write-baseline records the (clean) tree, --baseline
    compares against it, both exit 0."""
    base = tmp_path / "base.json"
    rc = cli.main(["--root", ROOT, "--pass", "guarded-by",
                   "--baseline", str(base), "--write-baseline"])
    assert rc == 0
    assert base.exists()
    rc = cli.main(["--root", ROOT, "--pass", "guarded-by",
                   "--baseline", str(base)])
    capsys.readouterr()
    assert rc == 0


def test_dump_graph_emits_guarded_by_table(capsys):
    rc = cli.main(["--root", ROOT, "--dump-graph"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "guarded-by table" in out
    assert "SocketParameterServer._clock <- SocketParameterServer._lock" in out
    assert "ReplicationFeed._lock -> SocketParameterServer._lock" in out


# -- TSAN wiring (ISSUE 14 sanitizer cell) -------------------------------------

@pytest.mark.slow
@pytest.mark.tsan
def test_native_hub_is_tsan_clean(tmp_path):
    """Compile the C++ hub with ``-fsanitize=thread`` together with the
    ``native/tsan_stress.cpp`` driver (sparse+adaptive primary, hot
    standby, inproc committers, socket pull/commit, sparse S/V/U, G/Y
    backpressure, M health, telemetry poller — concurrently) and fail
    on ANY ThreadSanitizer report.  This cell caught (and now pins the
    fixes for) the unsynchronized ``listen_fd_`` stop/accept race."""
    from conftest import require_tool

    require_tool("g++")
    probe = tmp_path / "probe.cpp"
    probe.write_text("int main() { return 0; }\n")
    if subprocess.run(["g++", "-fsanitize=thread", str(probe), "-o",
                       str(tmp_path / "probe")],
                      capture_output=True).returncode != 0:
        pytest.skip("g++ lacks -fsanitize=thread (no libtsan)")
    driver = tmp_path / "tsan_driver"
    build = subprocess.run(
        ["g++", "-fsanitize=thread", "-O1", "-g", "-pthread", "-std=c++17",
         "-ffp-contract=off",
         os.path.join(ROOT, "native", "ps_server.cpp"),
         os.path.join(ROOT, "native", "tsan_stress.cpp"),
         "-o", str(driver)],
        capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr
    env = dict(os.environ,
               TSAN_OPTIONS="exitcode=66 halt_on_error=0")
    proc = subprocess.run([str(driver)], capture_output=True, text=True,
                          timeout=240, env=env)
    out = proc.stdout + proc.stderr
    assert "WARNING: ThreadSanitizer" not in out, out[-4000:]
    assert proc.returncode == 0, out[-4000:]


def test_baseline_usage_errors_and_subset_write_preserves(tmp_path, capsys):
    """A missing/corrupt --baseline file is a usage error (exit 2, not a
    findings failure CI would misread), and --write-baseline with a
    --pass subset preserves the other passes' recorded suppressions."""
    missing = tmp_path / "nope.json"
    with pytest.raises(SystemExit) as e:
        cli.main(["--root", ROOT, "--pass", "guarded-by",
                  "--baseline", str(missing)])
    assert e.value.code == 2
    capsys.readouterr()
    torn = tmp_path / "torn.json"
    torn.write_text("{not json")
    with pytest.raises(SystemExit) as e:
        cli.main(["--root", ROOT, "--pass", "guarded-by",
                  "--baseline", str(torn)])
    assert e.value.code == 2
    capsys.readouterr()
    # subset refresh: a recorded telemetry entry survives a guarded-by
    # only --write-baseline (its pass did not run)
    base = tmp_path / "base.json"
    cli.write_baseline(str(base), {"telemetry": [
        Finding("telemetry", "pkg/x.py", 3, "bad name")]})
    rc = cli.main(["--root", ROOT, "--pass", "guarded-by",
                   "--baseline", str(base), "--write-baseline"])
    capsys.readouterr()
    assert rc == 0
    assert ("telemetry", "pkg/x.py", "bad name") in cli.load_baseline(
        str(base))


def test_lockset_instrument_skips_listed_subclasses():
    """Listing a base AND its subclass must not double-patch: each write
    on a subclass instance is observed exactly once (the inherited
    patched __setattr__ already covers it)."""
    import threading

    class Base:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

    class Sub(Base):
        pass

    with lockset.instrument(Base, Sub) as chk:
        s = Sub()
        s._n = 1
        s._n = 2
    assert chk.writes_checked == 3  # __init__'s _n=0 plus two stores
    # and both classes are fully restored
    assert "__setattr__" not in Base.__dict__
    assert "__setattr__" not in Sub.__dict__
