"""Ring attention correctness: sequence-parallel result must match dense
attention on the full sequence (8-way sequence sharding on the CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distkeras_tpu.ops.attention import dense_attention, ring_attention
from distkeras_tpu.parallel.mesh import create_mesh

SP = 8


def _run_ring(q, k, v, causal):
    mesh = create_mesh(SP, axis_name="sp")
    fn = jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    return np.asarray(fn(q, k, v))


def _rand_qkv(b=2, l=64, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    shape = (b, l, h, d)
    return (jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32))


def test_ring_matches_dense_causal():
    q, k, v = _rand_qkv()
    expected = np.asarray(dense_attention(q, k, v, causal=True))
    got = _run_ring(q, k, v, causal=True)
    np.testing.assert_allclose(got, expected, atol=1e-4)


def test_ring_matches_dense_noncausal():
    q, k, v = _rand_qkv(seed=1)
    expected = np.asarray(dense_attention(q, k, v, causal=False))
    got = _run_ring(q, k, v, causal=False)
    np.testing.assert_allclose(got, expected, atol=1e-4)


def test_dense_attention_causality():
    """Output at position t must not depend on keys/values after t."""
    q, k, v = _rand_qkv(b=1, l=16, h=1, d=4, seed=2)
    out1 = np.asarray(dense_attention(q, k, v, causal=True))
    k2 = k.at[:, 8:].set(999.0)
    v2 = v.at[:, 8:].set(999.0)
    out2 = np.asarray(dense_attention(q, k2, v2, causal=True))
    np.testing.assert_allclose(out1[:, :8], out2[:, :8], atol=1e-5)
