"""Ring attention correctness: sequence-parallel result must match dense
attention on the full sequence (8-way sequence sharding on the CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distkeras_tpu.ops.attention import dense_attention, ring_attention
from distkeras_tpu.parallel.mesh import create_mesh

SP = 8


def _run_ring(q, k, v, causal, impl="flash"):
    # impl="flash" by default so CPU tests exercise the TPU schedule (the
    # per-block flash kernel through the interpreter); the auto-select
    # would pick dense for these tiny shards
    mesh = create_mesh(SP, axis_name="sp")
    fn = jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp", causal=causal,
                                       impl=impl),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    return np.asarray(fn(q, k, v))


def _rand_qkv(b=2, l=64, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    shape = (b, l, h, d)
    return (jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32))


def test_ring_matches_dense_causal():
    q, k, v = _rand_qkv()
    expected = np.asarray(dense_attention(q, k, v, causal=True))
    for impl in ("flash", "dense"):
        got = _run_ring(q, k, v, causal=True, impl=impl)
        np.testing.assert_allclose(got, expected, atol=1e-4,
                                   err_msg=f"ring impl={impl}")


def test_ring_matches_dense_noncausal():
    q, k, v = _rand_qkv(seed=1)
    expected = np.asarray(dense_attention(q, k, v, causal=False))
    got = _run_ring(q, k, v, causal=False)
    np.testing.assert_allclose(got, expected, atol=1e-4)


def test_dense_attention_causality():
    """Output at position t must not depend on keys/values after t."""
    q, k, v = _rand_qkv(b=1, l=16, h=1, d=4, seed=2)
    out1 = np.asarray(dense_attention(q, k, v, causal=True))
    k2 = k.at[:, 8:].set(999.0)
    v2 = v.at[:, 8:].set(999.0)
    out2 = np.asarray(dense_attention(q, k2, v2, causal=True))
    np.testing.assert_allclose(out1[:, :8], out2[:, :8], atol=1e-5)


def test_ring_dead_steps_are_predicated():
    """Causal ring steps whose kv block is entirely in a rank's future must
    be skipped behind lax.cond (s = 1..sp-1), not merely masked — the jaxpr
    carries one cond per rotated step, and the non-causal schedule (every
    step live) carries none."""
    mesh = create_mesh(4, axis_name="sp")
    q, k, v = _rand_qkv(l=32)

    def count_conds(causal):
        fn = jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="sp", causal=causal,
                                           impl="flash"),
            mesh=mesh,
            in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"),
        ))
        jaxpr = str(jax.make_jaxpr(fn)(q, k, v))
        return jaxpr.count("cond[")

    causal_conds = count_conds(True)
    noncausal_conds = count_conds(False)
    # causal: one dead-step cond per rotated step (sp - 1 = 3) on top of
    # whatever the per-block kernel itself contributes (present in both)
    assert causal_conds - noncausal_conds == 3, (causal_conds, noncausal_conds)


def test_ring_gradients_match_dense():
    """Gradients through the flash-backed ring (incl. the lse cotangent
    path through the online merge) == dense attention gradients."""
    mesh = create_mesh(4, axis_name="sp")
    q, k, v = _rand_qkv(l=32, seed=3)

    def ring_loss(q, k, v):
        fn = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="sp", causal=True,
                                           impl="flash"),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"))
        o = fn(q, k, v)
        return jnp.sum(o * jnp.cos(o))

    def dense_loss(q, k, v):
        o = dense_attention(q, k, v, causal=True)
        return jnp.sum(o * jnp.cos(o))

    gr = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4,
                                   err_msg=f"ring/dense grad mismatch for {name}")


def test_ring_block_impl_area_rule(monkeypatch):
    """The flash/dense auto-select crossover tracks per-block WORK
    (l_local * head_dim >= 2048*64, measured on v5e at head_dim 64 and
    128 — see the docstring), is TPU-only, and requires 128-divisible
    block lengths."""
    from distkeras_tpu.ops import attention as att

    monkeypatch.setattr(att.jax, "default_backend", lambda: "tpu")
    assert att.ring_block_impl(2048, 64) == "flash"
    assert att.ring_block_impl(1024, 64) == "dense"   # 0.79x measured
    assert att.ring_block_impl(1024, 128) == "flash"  # 1.05x measured
    assert att.ring_block_impl(512, 128) == "dense"   # 0.72x measured
    assert att.ring_block_impl(2050, 64) == "dense"   # not 128-divisible
    monkeypatch.setattr(att.jax, "default_backend", lambda: "cpu")
    assert att.ring_block_impl(4096, 128) == "dense"  # interpret mode is slow
