"""Beam-search decoding: width-1 == greedy, score correctness, beam
dominance over greedy, EOS freezing, length penalty, guards."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models.base import Model
from distkeras_tpu.models.beam import beam_search, make_beam_search_fn
from distkeras_tpu.models.decode import generate
from distkeras_tpu.models.transformer import small_lm_spec


def _spec(**kw):
    cfg = dict(vocab_size=23, model_dim=32, num_heads=2, num_layers=2,
               max_seq_len=32)
    cfg.update(kw)
    spec = small_lm_spec(**cfg)
    spec.config["compute_dtype"] = "float32"  # tight parity tolerances
    return spec


@pytest.fixture(scope="module")
def model():
    return Model.init(_spec(), seed=11)


def _sequence_logprob(model, prompt, tokens):
    """Ground-truth total logprob of ``tokens`` continuing ``prompt``,
    via the O(L^2) full-forward (no cache): the number beam scores must
    reproduce."""
    seq = np.concatenate([np.asarray(prompt), np.asarray(tokens)], axis=1)
    total = np.zeros(seq.shape[0], np.float32)
    for t in range(prompt.shape[1], seq.shape[1]):
        logits = model.apply(jnp.asarray(seq[:, :t]))[:, -1]
        logp = np.asarray(jax.nn.log_softmax(logits.astype(jnp.float32)))
        total += logp[np.arange(seq.shape[0]), seq[:, t]]
    return total


def test_beam_width_1_is_greedy(model):
    prompt = jnp.asarray([[5, 17, 3], [2, 2, 9]], jnp.int32)
    want = np.asarray(generate(model, prompt, max_new_tokens=6))
    got, scores = beam_search(model, prompt, 6, beam_width=1)
    np.testing.assert_array_equal(np.asarray(got), want)
    np.testing.assert_allclose(np.asarray(scores),
                               _sequence_logprob(model, prompt, want),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.slow  # tier-1 budget (ISSUE 14 satellite): 7.5 s: exhaustive logprob oracle; beam_width_1/greedy parity stays in tier-1
def test_beam_scores_are_true_logprobs_and_beat_greedy(model):
    """Every returned beam's score must equal the sequence's true total
    logprob under the model, and the best beam must score >= the greedy
    sequence.  (The dominance half is NOT a theorem — beam search can
    prune greedy's continuation and end worse, observed on an 8k-vocab
    model on TPU — but it holds on this fixed seed/model/prompt, where
    it pins that the search actually explores rather than degenerating
    to width 1.)"""
    prompt = jnp.asarray([[7, 1, 19]], jnp.int32)
    fn = make_beam_search_fn(model.spec, 5, beam_width=4, return_all=True)
    toks, scores = fn(model.params, prompt)
    toks, scores = np.asarray(toks), np.asarray(scores)
    assert toks.shape == (1, 4, 5) and scores.shape == (1, 4)
    assert (np.diff(scores[0]) <= 1e-6).all(), "beams not sorted best-first"
    for wi in range(4):
        true = _sequence_logprob(model, prompt, toks[:, wi])
        np.testing.assert_allclose(scores[:, wi], true, rtol=1e-4, atol=1e-4)
    greedy = np.asarray(generate(model, prompt, max_new_tokens=5))
    g_score = _sequence_logprob(model, prompt, greedy)
    assert scores[0, 0] >= g_score[0] - 1e-4


def test_beam_eos_freezes_and_pads(model):
    """Declare the best beam's 2nd token as EOS: that beam must keep the
    EOS, pad afterwards, and report only the pre-EOS score."""
    prompt = jnp.asarray([[4, 12]], jnp.int32)
    free, _ = beam_search(model, prompt, 6, beam_width=3)
    eos = int(np.asarray(free)[0, 1])
    toks, scores = beam_search(model, prompt, 6, beam_width=3, eos_id=eos,
                               pad_id=0)
    toks = np.asarray(toks)
    hits = np.where(toks[0] == eos)[0]
    if hits.size:  # the winning beam may legitimately avoid EOS entirely
        first = hits[0]
        assert np.all(toks[0, first + 1:] == 0), toks
        clipped = toks[:, :first + 1]
        np.testing.assert_allclose(
            np.asarray(scores),
            _sequence_logprob(model, prompt, clipped), rtol=1e-4, atol=1e-4)


def test_length_penalty_changes_ranking_monotonically(model):
    """With alpha > 0 scores are divided by the GNMT factor: reported
    scores must equal raw scores normalized by each beam's length."""
    prompt = jnp.asarray([[3, 3, 14]], jnp.int32)
    raw_t, raw_s = make_beam_search_fn(model.spec, 4, beam_width=3,
                                       return_all=True)(model.params, prompt)
    pen_t, pen_s = make_beam_search_fn(model.spec, 4, beam_width=3,
                                       length_penalty=1.0,
                                       return_all=True)(model.params, prompt)
    # same beam set (no EOS -> all lengths 4): penalty divides uniformly,
    # so the ranking and members must match and scores scale by (9/6)
    np.testing.assert_array_equal(np.asarray(raw_t), np.asarray(pen_t))
    np.testing.assert_allclose(np.asarray(pen_s),
                               np.asarray(raw_s) / 1.5, rtol=1e-5)


def test_beam_guards(model):
    with pytest.raises(ValueError, match="beam_width"):
        make_beam_search_fn(model.spec, 4, beam_width=0)
    with pytest.raises(ValueError, match="max_seq_len"):
        beam_search(model, jnp.zeros((1, 30), jnp.int32), 10)
    with pytest.raises(ValueError, match="eos_id"):
        make_beam_search_fn(model.spec, 4, eos_id=99)
    sharded = _spec(seq_axis="sp")
    with pytest.raises(ValueError, match="plain"):
        make_beam_search_fn(sharded, 4)
