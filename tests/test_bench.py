"""bench.py contract: exactly one parseable JSON line on stdout, always.

Round-1 failure mode (VERDICT weak #2): a transient TPU-init error aborted
the bench with rc=1 and zero output, leaving the round with no perf
evidence.  The contract now is: main() never raises, and always prints one
JSON object with the headline metric keys — populated on success, zeroed
with an ``error`` note on failure.
"""

import json

import pytest

import bench


def _parse_single_json_line(capsys):
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, f"expected exactly one stdout line, got {out}"
    return json.loads(out[0])


def test_main_emits_metric_line(capsys, monkeypatch):
    monkeypatch.setattr(bench, "_bench_mnist_cnn",
                        lambda **kw: (123.4, bench._METHODOLOGY))
    bench.main()
    rec = _parse_single_json_line(capsys)
    assert rec["metric"] == "mnist_cnn_train_samples_per_sec_per_chip"
    assert rec["value"] == 123.4
    assert rec["unit"] == "samples/sec/chip"
    assert isinstance(rec["vs_baseline"], float)
    assert rec["platform"] == "cpu"  # conftest pins the CPU platform


def test_main_emits_diagnostic_line_on_failure(capsys, monkeypatch):
    def boom(**kw):
        raise RuntimeError("synthetic backend meltdown")

    monkeypatch.setattr(bench, "_bench_mnist_cnn", boom)
    bench.main()
    rec = _parse_single_json_line(capsys)
    assert rec["value"] == 0.0 and rec["vs_baseline"] == 0.0
    assert "synthetic backend meltdown" in rec["error"]
    assert rec["metric"] == "mnist_cnn_train_samples_per_sec_per_chip"


@pytest.mark.slow  # tier-1 budget fix (PR 11): heaviest cells ride the full suite
def test_mnist_bench_runs_on_cpu():
    sps, method = bench._bench_mnist_cnn(batch_size=8, num_batches=2, reps=1)
    assert sps > 0
    # the profiler trace has no device module events on CPU: the tag must
    # say WALL so the ratio logic refuses a device-keyed baseline
    assert method == bench._METHODOLOGY_WALL


def test_peak_flops_lookup():
    assert bench._peak_flops("TPU v5 lite") == 197e12
    assert bench._peak_flops("TPU v5p chip") == 459e12
    assert bench._peak_flops("Quantum Abacus 9000") is None


@pytest.mark.slow  # tier-1 budget fix (PR 11): heaviest cells ride the full suite
def test_decode_bench_runs_tiny_on_cpu():
    """The decode section (incl. the TRAINED speculative leg) at toy scale:
    every leg present, spread recorded, acceptance_rate a real fraction."""
    out = bench._bench_decode(batch=2, prompt_len=8, new_tokens=16,
                              model_dim=32, num_heads=2, num_layers=2,
                              vocab=64, reps=2, train_steps=8)
    for mode in ("fp", "int8", "fp_b1", "fp_b1_trained", "speculative_b1",
                 "speculative_batched"):
        assert out[mode]["tokens_per_sec"] > 0, mode
        assert "wall_spread" in out[mode], mode
    for sp in (out["speculative_b1"], out["speculative_batched"]):
        assert sp["trained"] is True
        assert 0.0 <= sp["acceptance_rate"] <= 1.0
    assert out["speculative_speedup_vs_fp_batched"] > 0
    # CPU trace may or may not yield module events; the tag must say which
    assert out["timing"] in ("device-median-of-2", "wall-median-of-2")
    assert out["speculative_speedup_vs_fp_b1"] > 0


def test_ring_bench_runs_tiny_on_cpu():
    if not hasattr(__import__("jax"), "shard_map"):
        pytest.skip("jax.shard_map unavailable (ring attention needs it)")
    leg = bench._bench_ring(256, batch=1, heads=2, head_dim=64, steps=1)
    assert leg["l_local"] == 256
    assert leg["flash_ms"] > 0 and leg["dense_ms"] > 0
    assert leg["auto_selects"] == "dense"
    assert leg["timing"] in ("device", "wall")


def test_lm_leg_baseline_keys_include_heads():
    """A heads change must break the baseline match (no bogus ratio)."""
    out = {"lm": [{"seq_len": 2048, "batch": 8, "model_dim": 512,
                   "num_heads": 4, "timing": "device",
                   "tokens_per_sec": 100.0}]}
    baseline = {"legs": {"lm:2048x8:d512h8": {"tokens_per_sec": 50.0}}}
    bench._apply_leg_baselines(out, baseline)
    assert "vs_baseline" not in out["lm"][0]
    out["lm"][0]["num_heads"] = 8
    bench._apply_leg_baselines(out, baseline)
    assert out["lm"][0]["vs_baseline"] == 2.0


def test_ring_baseline_ratio_inverted():
    leg = {"l_local": 2048, "batch": 1, "heads": 8, "head_dim": 64,
           "flash_ms": 2.0, "timing": "device"}
    out = {"ring": [dict(leg)]}
    baseline = {"legs": {"ring:2048:b1h8d64:device": {"flash_ms": 4.0}}}
    bench._apply_leg_baselines(out, baseline)
    assert out["ring"][0]["vs_baseline"] == 2.0  # faster than recorded best

    # a wall-fallback leg must NOT ratio against the device record
    wall = {"ring": [dict(leg, timing="wall")]}
    bench._apply_leg_baselines(wall, baseline)
    assert "vs_baseline" not in wall["ring"][0]

    # a config change (different heads) must break the match
    other = {"ring": [dict(leg, heads=4)]}
    bench._apply_leg_baselines(other, baseline)
    assert "vs_baseline" not in other["ring"][0]


def test_lm_wall_fallback_skips_baseline():
    out = {"lm": [{"seq_len": 2048, "batch": 8, "model_dim": 512,
                   "num_heads": 8, "timing": "wall", "tokens_per_sec": 100.0}]}
    baseline = {"legs": {"lm:2048x8:d512h8": {"tokens_per_sec": 50.0}}}
    bench._apply_leg_baselines(out, baseline)
    assert "vs_baseline" not in out["lm"][0]


@pytest.mark.slow  # ~10-70s of bench machinery; the full suite runs it
def test_feed_bench_sweep_and_decomposition_tiny_on_cpu():
    """The feed leg's round-6 shape: a chunk-size sweep whose best config
    is promoted to the headline comparison, plus a per-chunk IO/wire/step
    decomposition — all at toy scale."""
    out = bench._bench_feed(batch=16, total_batches=8, reps=1,
                            sweep_batches_per_chunk=(2, 4), sweep_reps=1)
    assert len(out["sweep"]) == 2
    assert {"batches_per_chunk", "chunk_mb", "prefetch_ms",
            "samples_per_sec"} <= set(out["sweep"][0])
    assert out["best_chunk_mb"] in {s["chunk_mb"] for s in out["sweep"]}
    # the headline comparison ran AT the promoted best size
    assert out["chunk_mb"] == out["best_chunk_mb"]
    dec = out["decomposition"]
    for k in ("io_ms_per_chunk", "wire_ms_per_chunk",
              "step_wall_ms_per_chunk", "device_ms_per_chunk"):
        assert dec[k] >= 0.0, k
    assert out["compute_only_ms"] > 0 and out["prefetch_ms"] > 0


@pytest.mark.slow  # ~10-70s of bench machinery; the full suite runs it
def test_moe_capacity_sweep_tiny_on_cpu():
    """Trained-router capacity sweep machinery at toy scale: drops are
    recorded untrained AND trained per factor, and training reduces them
    at generous capacity (the aux loss is in the objective)."""
    sweep = bench._bench_moe_capacity_sweep(
        model_dim=16, num_heads=2, vocab=64, experts=4, batch=2, seq_len=16,
        num_layers=1, steps=40, factors=(1.0, 2.0))
    import numpy as np

    assert [s["capacity_factor"] for s in sweep] == [1.0, 2.0]
    for s in sweep:
        assert 0.0 <= s["dropped_fraction_trained"] <= 1.0
        assert 0.0 <= s["dropped_fraction_untrained"] <= 1.0
        assert s["capacity"] >= 1 and np.isfinite(s["final_loss"])


def test_moe_baseline_keys_cover_dispatch_legs():
    """top1 (sorted, default) and top1_dense ratio against SEPARATE
    baseline records; a wall-fallback leg must not ratio at all."""
    moe = {"batch": 4, "seq_len": 512, "experts": 8,
           "top1": {"timing": "device", "tokens_per_sec": 400.0},
           "top1_dense": {"timing": "device", "tokens_per_sec": 250.0},
           "top2": {"timing": "wall", "tokens_per_sec": 300.0}}
    baseline = {"legs": {
        "moe:top1:b4s512e8:device": {"tokens_per_sec": 253.2},
        "moe:top1_dense:b4s512e8:device": {"tokens_per_sec": 250.0}}}
    out = {"moe": moe}
    bench._apply_leg_baselines(out, baseline)
    assert moe["top1"]["vs_baseline"] == round(400.0 / 253.2, 4)
    assert moe["top1_dense"]["vs_baseline"] == 1.0
    assert "vs_baseline" not in moe["top2"]  # wall fallback


def test_async_baseline_keys_cover_new_legs():
    asy = {"workers": 2, "window": 8, "batch": 256,
           "async_adag_native": {"per_window_device_ms": 2.0},
           "async_adag_int8": {"per_window_device_ms": 4.0},
           "async_adag_inproc": {"per_window_device_ms": 3.0}}
    baseline = {"legs": {
        "async:async_adag_native:w2x8b256:device-window":
            {"per_window_device_ms": 4.0},
        "async:async_adag_inproc:w2x8b256:device-window":
            {"per_window_device_ms": 6.0}}}
    out = {"async": asy}
    bench._apply_leg_baselines(out, baseline)
    assert asy["async_adag_native"]["vs_baseline"] == 2.0  # ms inverted
    assert asy["async_adag_inproc"]["vs_baseline"] == 2.0  # ms inverted
    assert "vs_baseline" not in asy["async_adag_int8"]  # no record yet


def test_async_acceptance_block_tripwires():
    """The issue-3 acceptance block: vs-sync ratios + r05 speedup + final-
    loss parity, with None (not a crash) wherever a leg errored out."""
    out = {
        "async_adag": {"samples_per_sec": 9000.0, "per_window_wall_ms": 42.0,
                       "final_loss": 0.51},
        "async_adag_inproc": {"samples_per_sec": 9500.0},
        "async_adag_serial": {"samples_per_sec": 4800.0, "final_loss": 0.52},
        "sync_adag": {"samples_per_sec": 10000.0},
    }
    bench._async_acceptance(out)
    acc = out["acceptance"]
    assert out["adag_vs_sync"] == 0.9 and acc["adag_vs_sync_ok"] is True
    assert out["adag_inproc_vs_sync"] == 0.95 and acc["inproc_vs_sync_ok"] is True
    assert acc["per_window_speedup_vs_r05"] == round(421.15 / 42.0, 2)
    assert acc["per_window_speedup_ok"] is True
    assert acc["final_loss_parity"]["abs_diff"] == 0.01

    # a dead sync denominator degrades to None tripwires, not a KeyError
    out2 = {"async_adag": {"samples_per_sec": 9000.0,
                           "per_window_wall_ms": 500.0, "final_loss": 0.5},
            "sync_adag": {"error": "AttributeError: no shard_map"}}
    bench._async_acceptance(out2)
    acc2 = out2["acceptance"]
    assert "adag_vs_sync" not in out2
    assert acc2["adag_vs_sync_ok"] is None and acc2["inproc_vs_sync_ok"] is None
    assert acc2["per_window_speedup_ok"] is False  # 500ms > 421.15/5
    assert acc2["final_loss_parity"] is None


def test_async_transport_acceptance_tripwires():
    """The ISSUE-18 zero-copy tripwires: shm-ring per-window wall must
    beat the inproc direct pair, and the recv_batch hub must have served
    more than one frame per blocking fill — None-degrading like every
    other acceptance boolean."""
    out = {
        "async_adag_inproc": {"per_window_wall_ms": 40.0},
        "shm_ring": {"per_window_wall_ms": 38.0},
        "recv_batch": {"per_window_wall_ms": 41.0, "decomposition": {
            "recv_batch_depth": {"count": 6, "mean": 2.5, "max": 4}}},
    }
    bench._async_acceptance(out)
    acc = out["acceptance"]
    assert acc["shm_vs_inproc_per_window"] == 0.95
    assert acc["shm_beats_inproc_direct_ok"] is True
    assert acc["batch_syscalls_ok"] is True

    # a slower ring trips the wire; a depth that never batched trips too
    out2 = {
        "async_adag_inproc": {"per_window_wall_ms": 40.0},
        "shm_ring": {"per_window_wall_ms": 44.0},
        "recv_batch": {"per_window_wall_ms": 41.0, "decomposition": {
            "recv_batch_depth": {"count": 6, "mean": 1.0, "max": 1}}},
    }
    bench._async_acceptance(out2)
    assert out2["acceptance"]["shm_beats_inproc_direct_ok"] is False
    assert out2["acceptance"]["batch_syscalls_ok"] is False

    # dead/missing legs degrade to None, not a KeyError
    out3 = {"shm_ring": {"error": "OSError: /dev/shm full"},
            "recv_batch": {"per_window_wall_ms": 41.0}}
    bench._async_acceptance(out3)
    assert out3["acceptance"]["shm_vs_inproc_per_window"] is None
    assert out3["acceptance"]["shm_beats_inproc_direct_ok"] is None
    assert out3["acceptance"]["batch_syscalls_ok"] is None


@pytest.mark.slow  # trains real (tiny) models; the full suite runs it
def test_bench_async_transport_legs_tiny_e2e():
    """The evidence sources the shm_ring/recv_batch bench legs consume,
    end to end at toy scale: an shm run moves frames over the rings
    (ps.shm_frames_total), and a batched hub records its frames-per-fill
    histogram (ps_recv_batch_depth) — the batch tripwire's input."""
    import numpy as np

    from distkeras_tpu import observability as obs
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG

    spec = ModelSpec(name="mlp",
                     config={"hidden_sizes": (8,), "num_outputs": 2},
                     input_shape=(4,))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = rng.integers(0, 2, size=64)
    ds = Dataset({"features": x, "label": np.eye(2, dtype=np.float32)[y]})
    kwargs = dict(loss="categorical_crossentropy", batch_size=16,
                  num_epoch=1, num_workers=2, communication_window=2,
                  learning_rate=0.05, seed=0)
    obs.reset()
    obs.enable()
    try:
        AsyncADAG(Model.init(spec, seed=0), transport="shm",
                  **kwargs).train(ds)
        snap = obs.snapshot()
        assert snap["counters"].get("ps.shm_frames_total", 0) > 0
        obs.reset()
        AsyncADAG(Model.init(spec, seed=0), recv_batch_depth=8,
                  **kwargs).train(ds)
        hist = obs.snapshot()["histograms"].get("ps_recv_batch_depth")
        assert hist is not None and hist["count"] >= 1
    finally:
        obs.disable()
        obs.reset()


def test_async_shard_acceptance_block_tripwires():
    """The ISSUE-6 shard-scaling tripwire: >= 3x aggregate commit
    throughput at 4 shards vs 1, None-degrading (the PR-3 convention)
    when either leg is missing or errored."""
    out = {"1": {"commits_per_sec": 100.0}, "4": {"commits_per_sec": 320.0}}
    bench._async_shard_acceptance(out)
    acc = out["acceptance"]
    assert acc["shard_scaling_target"] == 3.0
    assert acc["scaling_x_4_vs_1"] == 3.2
    assert acc["shard_scaling_ok"] is True

    out2 = {"1": {"commits_per_sec": 100.0}, "4": {"commits_per_sec": 250.0}}
    bench._async_shard_acceptance(out2)
    assert out2["acceptance"]["shard_scaling_ok"] is False

    # a dead leg degrades to None tripwires, not a KeyError/ZeroDivision
    out3 = {"1": {"error": "ConnectionError: hub process died"},
            "4": {"commits_per_sec": 250.0}}
    bench._async_shard_acceptance(out3)
    assert out3["acceptance"]["scaling_x_4_vs_1"] is None
    assert out3["acceptance"]["shard_scaling_ok"] is None

    out4 = {"1": {"commits_per_sec": 0.0}, "4": {"commits_per_sec": 250.0}}
    bench._async_shard_acceptance(out4)
    assert out4["acceptance"]["shard_scaling_ok"] is None  # zero denominator

    out5 = {}  # both legs missing entirely
    bench._async_shard_acceptance(out5)
    assert out5["acceptance"]["shard_scaling_ok"] is None


@pytest.mark.slow  # spawns ~6 processes; the full suite runs it
def test_async_shard_bench_runs_tiny():
    """The shard-scaling leg end to end at toy scale: both legs produce
    throughput figures, the per-shard decomposition covers every shard,
    and every shard applied every logical commit."""
    out = bench._bench_async_shards(shard_counts=(1, 2), workers=2,
                                    leaves=4, leaf_elems=256,
                                    commits_per_worker=8)
    for key, shards in (("1", 1), ("2", 2)):
        leg = out[key]
        assert leg["commits_per_sec"] > 0
        assert set(leg["per_shard"]) == {str(s) for s in range(shards)}
        for sb in leg["per_shard"].values():
            assert sb["commits"] == leg["logical_commits"]
            assert sb["wire_mb"] > 0
    # acceptance needs the 1 and 4 legs; a (1, 2) run degrades to None
    assert out["acceptance"]["shard_scaling_ok"] is None


def test_async_recovery_acceptance_block_tripwires():
    """The issue-4 recovery acceptance block: recovered/parity booleans,
    with None (not a crash) wherever a denominator leg errored out."""
    out = {
        "fault_free": {"wall_s": 10.0, "final_loss": 2.0},
        "sever": {"wall_s": 14.0, "final_loss": 2.1, "faults_fired": 2,
                  "reconnects": 2.0, "recovery_ms": {"count": 2}},
        "worker_restart": {"wall_s": 13.0, "final_loss": 2.05,
                           "kills_fired": 1, "restarts": 1,
                           "worker_errors": 0},
    }
    bench._async_recovery_acceptance(out)
    acc = out["acceptance"]
    assert acc["sever_recovered_ok"] is True
    assert acc["sever_loss_abs_diff"] == 0.1
    assert acc["sever_loss_tol"] == 0.3  # max(0.05, 0.15 * 2.0)
    assert acc["sever_loss_parity_ok"] is True
    assert acc["worker_restart_ok"] is True
    assert acc["restart_loss_parity_ok"] is True

    # a dead fault-free denominator degrades parity to None, and a dead
    # chaos leg degrades its own tripwires — nothing raises
    out2 = {
        "fault_free": {"error": "RuntimeError: device fell over"},
        "sever": {"error": "ConnectionError: proxy died"},
        "worker_restart": {"wall_s": 13.0, "final_loss": 2.05,
                           "kills_fired": 1, "restarts": 1,
                           "worker_errors": 0},
    }
    bench._async_recovery_acceptance(out2)
    acc2 = out2["acceptance"]
    assert acc2["sever_recovered_ok"] is None
    assert acc2["sever_loss_parity_ok"] is None
    assert acc2["worker_restart_ok"] is True
    assert acc2["restart_loss_parity_ok"] is None
    # legs absent entirely (issue-7 failover + barrier): None, not a crash
    assert acc2["failover_recovered_ok"] is None
    assert acc2["failover_ms_recorded"] is None
    assert acc2["failover_loss_parity_ok"] is None
    assert acc2["snapshot_barrier_ok"] is None


def test_failover_acceptance_block_tripwires():
    """The issue-7 failover/barrier tripwires: recovered means the kill
    fired, workers failed over, the standby promoted and its clock AT
    PROMOTION respects the zero-ACKED-loss bound (kill clock minus the
    in-flight slack — end-of-run counts are inflated by post-failover
    commits and prove nothing); the barrier tripwire pins <5%
    commit-throughput overhead.  All None-degrading."""
    out = {
        "fault_free": {"wall_s": 10.0, "final_loss": 2.0},
        "sever": {"error": "skipped"},
        "worker_restart": {"error": "skipped"},
        "failover": {"wall_s": 15.0, "final_loss": 2.08,
                     "killed_at_clock": 16, "promoted_at_clock": 14,
                     "replica_commits": 40,
                     "acked_loss_slack": 4, "promoted": True,
                     "failovers": 2.0,
                     "failover_ms": {"count": 2, "mean": 180.0, "max": 300.0}},
        "snapshot_barrier": {"overhead_pct": 2.4},
    }
    bench._async_recovery_acceptance(out)
    acc = out["acceptance"]
    assert acc["failover_recovered_ok"] is True
    assert acc["failover_ms_recorded"] is True
    assert acc["failover_loss_abs_diff"] == 0.08
    assert acc["failover_loss_parity_ok"] is True
    assert acc["snapshot_barrier_overhead_pct"] == 2.4
    assert acc["snapshot_barrier_ok"] is True

    # acked-commit loss beyond the in-flight slack flips recovered to
    # False — judged at PROMOTION time, so a post-failover-inflated
    # replica_commits (40 here) cannot mask it
    out["failover"]["promoted_at_clock"] = 11
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["failover_recovered_ok"] is False
    # a heavy barrier flips its tripwire
    out["snapshot_barrier"] = {"overhead_pct": 9.0}
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["snapshot_barrier_ok"] is False
    # an errored barrier leg degrades, never crashes
    out["snapshot_barrier"] = {"error": "OSError: disk full"}
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["snapshot_barrier_ok"] is None


def test_adaptive_acceptance_block_tripwires():
    """The issue-10 adaptive tripwires: adaptive beats plain final loss
    at comparable wall (ratio <= 1.25), and the control loop visibly
    reacted (merged or rate-scaled >= 1 commit) — None-degrading when
    either leg errored or the whole sub-leg is missing."""
    out = {
        "fault_free": {"wall_s": 10.0, "final_loss": 2.0},
        "sever": {"error": "skipped"},
        "worker_restart": {"error": "skipped"},
        "adaptive": {
            "plain": {"wall_s": 10.0, "final_loss": 2.30,
                      "merged_commits": 0.0, "rate_scaled_commits": 0.0},
            "adaptive": {"wall_s": 11.0, "final_loss": 2.10,
                         "merged_commits": 5.0,
                         "rate_scaled_commits": 3.0},
        },
    }
    bench._async_recovery_acceptance(out)
    acc = out["acceptance"]
    assert acc["adaptive_plain_final_loss"] == 2.30
    assert acc["adaptive_final_loss"] == 2.10
    assert acc["adaptive_wall_ratio"] == 1.1
    assert acc["adaptive_beats_plain_ok"] is True
    assert acc["adaptive_reacted_ok"] is True

    # adaptive landing WORSE than plain flips the tripwire
    out["adaptive"]["adaptive"]["final_loss"] = 2.50
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["adaptive_beats_plain_ok"] is False
    # equal-work walls drifting apart invalidates the comparison too
    out["adaptive"]["adaptive"]["final_loss"] = 2.10
    out["adaptive"]["adaptive"]["wall_s"] = 20.0
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["adaptive_beats_plain_ok"] is False
    # a control loop that never reacted is its own failure
    out["adaptive"]["adaptive"]["wall_s"] = 11.0
    out["adaptive"]["adaptive"]["merged_commits"] = 0.0
    out["adaptive"]["adaptive"]["rate_scaled_commits"] = 0.0
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["adaptive_reacted_ok"] is False

    # an errored plain leg degrades the comparison (not the reaction
    # check); a missing sub-leg degrades everything — never a crash
    out["adaptive"]["adaptive"]["merged_commits"] = 5.0
    out["adaptive"]["plain"] = {"error": "ConnectionError: proxy died"}
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["adaptive_beats_plain_ok"] is None
    assert out["acceptance"]["adaptive_reacted_ok"] is True
    del out["adaptive"]
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["adaptive_beats_plain_ok"] is None
    assert out["acceptance"]["adaptive_reacted_ok"] is None
    assert out["acceptance"]["adaptive_wall_ratio"] is None


def test_spot_preemption_acceptance_block_tripwires():
    """The ISSUE-19 tripwires: preemption_recovered_ok pins every planned
    notice fired + respawned with zero operator input and >= 90% of the
    pre-preemption windows/s restored; drain_zero_loss_ok separately pins
    that every drain completed clean with nothing outstanding.  Both
    None-degrade when the leg errored or never measured a rate."""
    sp = {
        "workers": 6, "preempt": 2, "preemptions_fired": 2,
        "drains": [{"worker": 4, "drained_clean": True,
                    "outstanding_after_drain": 0},
                   {"worker": 5, "drained_clean": True,
                    "outstanding_after_drain": 0}],
        "drains_clean": True, "outstanding_after_drain": 0,
        "respawns": 2, "pre_rate_windows_s": 100.0,
        "post_rate_windows_s": 95.0, "restarts": 0, "worker_errors": 0,
    }
    out = {
        "fault_free": {"wall_s": 10.0, "final_loss": 2.0},
        "sever": {"error": "skipped"},
        "worker_restart": {"error": "skipped"},
        "spot_preemption": dict(sp),
    }
    bench._async_recovery_acceptance(out)
    acc = out["acceptance"]
    assert acc["preemption_pre_rate_windows_s"] == 100.0
    assert acc["preemption_post_rate_windows_s"] == 95.0
    assert acc["preemption_recovered_ok"] is True
    assert acc["drain_zero_loss_ok"] is True

    # < 90% throughput restored flips recovered (the acceptance floor)
    out["spot_preemption"] = dict(sp, post_rate_windows_s=80.0)
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["preemption_recovered_ok"] is False
    assert out["acceptance"]["drain_zero_loss_ok"] is True
    # a missing respawn (operator input needed) flips recovered
    out["spot_preemption"] = dict(sp, respawns=1)
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["preemption_recovered_ok"] is False
    # an unclean drain or leftover in-flight commit flips zero-loss
    out["spot_preemption"] = dict(sp, drains_clean=False)
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["drain_zero_loss_ok"] is False
    out["spot_preemption"] = dict(sp, outstanding_after_drain=1)
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["drain_zero_loss_ok"] is False
    # a drain that never fired its notice count flips zero-loss too
    out["spot_preemption"] = dict(sp, drains=sp["drains"][:1])
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["drain_zero_loss_ok"] is False

    # no rate measured -> recovered degrades to None; an errored or
    # absent leg degrades everything — never a crash
    out["spot_preemption"] = dict(sp, pre_rate_windows_s=None)
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["preemption_recovered_ok"] is None
    out["spot_preemption"] = {"error": "RuntimeError: hub fell over"}
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["preemption_recovered_ok"] is None
    assert out["acceptance"]["drain_zero_loss_ok"] is None
    assert out["acceptance"]["preemption_pre_rate_windows_s"] is None
    del out["spot_preemption"]
    bench._async_recovery_acceptance(out)
    assert out["acceptance"]["preemption_recovered_ok"] is None
    assert out["acceptance"]["drain_zero_loss_ok"] is None


@pytest.mark.slow
def test_bench_async_spot_preemption_tiny_e2e():
    """The spot-preemption bench leg end to end at a CI-scale shape:
    notices fire, drains complete clean, respawns are budget-neutral."""
    out = bench._bench_async_spot_preemption(workers=4, preempt=1,
                                             window=2, batch=16,
                                             windows_per_epoch=4, epochs=2)
    assert "error" not in out, out
    assert out["preemptions_fired"] == 1
    assert out["drains_clean"] is True
    assert out["outstanding_after_drain"] == 0
    assert out["respawns"] >= 1
    assert out["restarts"] == 0
    assert out["worker_errors"] == 0


@pytest.mark.slow
def test_bench_async_adaptive_tiny_e2e():
    """The adaptive bench leg end to end at a CI-scale shape: both legs
    run, record losses/walls, and the adaptive leg's counters exist."""
    out = bench._bench_async_adaptive(workers=2, window=2, batch=16,
                                      windows_per_epoch=2, epochs=1,
                                      jitter_s=(0.001, 0.002))
    for name in ("plain", "adaptive"):
        leg = out[name]
        assert "error" not in leg, leg
        assert leg["final_loss"] is not None
        assert leg["wall_s"] > 0
    assert out["adaptive"]["merged_commits"] >= 0.0


def test_observability_acceptance_block_tripwires():
    """The issue-5 tripwire block: tracing overhead under the 3% target,
    >=95% commit-context coverage, straggler ranking present — with None
    (not a crash) wherever a leg is missing."""
    out = {
        "overhead_pct": 1.4,
        "fleet": {"commit_context_coverage": 0.99, "total_commits": 96,
                  "top_straggler": "1", "workers_seen": 2},
    }
    bench._observability_acceptance(out)
    acc = out["acceptance"]
    assert acc["overhead_ok"] is True and acc["overhead_pct_target"] == 3.0
    assert acc["coverage_ok"] is True and acc["coverage_target"] == 0.95
    assert acc["straggler_ranked"] is True

    out2 = {"overhead_pct": 5.2,
            "fleet": {"commit_context_coverage": 0.5, "top_straggler": None}}
    bench._observability_acceptance(out2)
    acc2 = out2["acceptance"]
    assert acc2["overhead_ok"] is False
    assert acc2["coverage_ok"] is False
    assert acc2["straggler_ranked"] is False

    out3 = {}  # the whole leg errored before measuring anything
    bench._observability_acceptance(out3)
    acc3 = out3["acceptance"]
    assert acc3["overhead_ok"] is None
    assert acc3["coverage_ok"] is None
    assert acc3["straggler_ranked"] is None


def test_health_acceptance_block_tripwires():
    """The issue-8 tripwire block: the fully-on health plane (tracking +
    streaming collector + detectors) under the 3% wall-overhead target,
    fleet coverage (every worker reported), reports actually ingested —
    with None (not a crash) wherever a leg is missing."""
    out = {
        "workers": 2,
        "overhead_pct": 1.1,
        "collector": {"workers_seen": 2, "reports_ingested": 6,
                      "tracked_series": 4, "events": 0},
    }
    bench._health_acceptance(out)
    acc = out["acceptance"]
    assert acc["overhead_ok"] is True and acc["overhead_pct_target"] == 3.0
    assert acc["fleet_covered"] is True
    assert acc["reports_ok"] is True

    out2 = {"workers": 4, "overhead_pct": 4.9,
            "collector": {"workers_seen": 2, "reports_ingested": 0}}
    bench._health_acceptance(out2)
    acc2 = out2["acceptance"]
    assert acc2["overhead_ok"] is False
    assert acc2["fleet_covered"] is False
    assert acc2["reports_ok"] is False

    out3 = {}  # the whole leg errored before measuring anything
    bench._health_acceptance(out3)
    acc3 = out3["acceptance"]
    assert acc3["overhead_ok"] is None
    assert acc3["fleet_covered"] is None
    assert acc3["reports_ok"] is None


def test_embedding_acceptance_block_tripwires():
    """The issue-9 tripwire block: sparse exchange bytes under
    1.1 x touched-row fraction of the dense leg, rows/s recorded — with
    None (not a crash) wherever a leg is missing (PR-3 convention)."""
    out = {
        "dense": {"wall_s": 1.0, "wire_bytes": 110_000_000,
                  "exchange_bytes": 100_000_000},
        "sparse": {"wall_s": 0.5, "wire_bytes": 2_000_000,
                   "exchange_bytes": 1_000_000, "rows_per_s": 5000.0,
                   "touched_row_fraction": 0.01},
    }
    bench._embedding_acceptance(out)
    acc = out["acceptance"]
    assert acc["wire_ratio"] == 0.01
    assert acc["wire_ratio_bound"] == 0.011
    assert acc["sparse_wire_ok"] is True
    assert acc["rows_per_s_recorded"] is True

    out2 = {
        "dense": {"exchange_bytes": 100_000_000},
        "sparse": {"exchange_bytes": 2_000_000, "rows_per_s": 5000.0,
                   "touched_row_fraction": 0.01},
    }
    bench._embedding_acceptance(out2)
    assert out2["acceptance"]["sparse_wire_ok"] is False  # 0.02 > 0.011

    out3 = {"dense": {"error": "boom"}}  # sparse leg never ran
    bench._embedding_acceptance(out3)
    acc3 = out3["acceptance"]
    assert acc3["sparse_wire_ok"] is None
    assert acc3["wire_ratio"] is None
    assert acc3["rows_per_s_recorded"] is None

    out4 = {}  # the whole leg errored before measuring anything
    bench._embedding_acceptance(out4)
    assert out4["acceptance"]["sparse_wire_ok"] is None


def test_embedding_hot_tier_acceptance_tripwires():
    """The issue-15 tripwire block: replication bytes under 1.1 x the
    touched-row fraction of the dense-R equivalent, client cache memory
    scaling with the hot fraction, warm hit rate recorded — with None
    (not a crash) wherever the hot leg is missing (PR-3 convention)."""
    out = {
        "dense": {"exchange_bytes": 100_000_000},
        "sparse": {"exchange_bytes": 1_000_000, "rows_per_s": 5000.0,
                   "touched_row_fraction": 0.01},
        "hot": {"repl_sparse_bytes": 1_000_000,
                "repl_dense_equiv_bytes": 100_000_000,
                "touched_row_fraction": 0.01,
                "cache_memory_ratio": 0.02, "hot_fraction": 0.01,
                "cache_hit_rate": 0.8},
    }
    bench._embedding_acceptance(out)
    acc = out["acceptance"]
    assert acc["repl_ratio"] == 0.01
    assert acc["repl_ratio_bound"] == 0.011
    assert acc["repl_sparse_ok"] is True
    assert acc["cache_memory_ok"] is True  # 0.02 <= 4 x 0.01
    assert acc["cache_hit_ok"] is True

    out2 = dict(out)
    out2["hot"] = {"repl_sparse_bytes": 2_000_000,
                   "repl_dense_equiv_bytes": 100_000_000,
                   "touched_row_fraction": 0.01,
                   "cache_memory_ratio": 0.2, "hot_fraction": 0.01,
                   "cache_hit_rate": 0.1}
    bench._embedding_acceptance(out2)
    acc2 = out2["acceptance"]
    assert acc2["repl_sparse_ok"] is False  # 0.02 > 0.011
    assert acc2["cache_memory_ok"] is False  # 0.2 > 0.04
    assert acc2["cache_hit_ok"] is False

    out3 = {"dense": {"exchange_bytes": 1},
            "sparse": {"exchange_bytes": 1},
            "hot": {"error": "boom"}}  # hot leg degraded, PR-9 legs live
    bench._embedding_acceptance(out3)
    acc3 = out3["acceptance"]
    assert acc3["repl_sparse_ok"] is None
    assert acc3["cache_memory_ok"] is None
    assert acc3["cache_hit_ok"] is None

    out4 = {}
    bench._embedding_acceptance(out4)
    assert out4["acceptance"]["repl_sparse_ok"] is None


@pytest.mark.slow  # ~60-200s of real bench machinery on CPU
def test_embedding_bench_runs_tiny():
    """End-to-end smoke of the issue-9 leg at toy scale: both legs run,
    the tripwire block attaches, the sparse leg actually moved fewer
    exchange bytes than the dense leg and counted its rows.  (The toy
    shape's dense head is NOT negligible next to the toy table, so the
    1.1x bound itself is asserted only at the real bench shape.)"""
    out = bench._bench_embedding(rows=2048, dim=32, fields=2, batch=8,
                                 window=2, windows_per_epoch=2, epochs=1,
                                 workers=1, reps=1)
    assert "acceptance" in out
    assert out["dense"]["exchange_bytes"] > 0
    assert out["sparse"]["exchange_bytes"] > 0
    assert out["sparse"]["exchange_bytes"] < out["dense"]["exchange_bytes"]
    assert out["sparse"]["rows_committed"] > 0
    assert out["acceptance"]["rows_per_s_recorded"] is True
    assert out["acceptance"]["wire_ratio"] is not None
    # issue-15 hot leg: the standby saw row-delta frames, the bounded
    # cache was smaller than the table, hits landed (the 1.1x bounds are
    # asserted at the real shape only — the toy head is not negligible)
    hot = out["hot"]
    assert hot["repl_sparse_bytes"] > 0
    assert hot["repl_sparse_bytes"] < hot["repl_dense_equiv_bytes"]
    assert hot["cache_bytes"] < hot["full_cache_bytes"]
    assert hot["cache_hits"] > 0
    assert out["acceptance"]["cache_memory_ok"] is True
    assert out["acceptance"]["repl_ratio"] is not None


@pytest.mark.slow  # ~60-200s of real bench machinery on CPU
def test_health_bench_runs_tiny():
    """End-to-end smoke of the issue-8 leg at toy scale: both sub-legs
    run, the tripwire block attaches, and the on-leg's collector actually
    saw every worker's reports."""
    out = bench._bench_health(workers=2, window=2, batch=8,
                              windows_per_epoch=2, epochs=1, reps=1,
                              health_interval_s=0.05)
    assert "acceptance" in out
    assert out["health_off"]["wall_s"] > 0
    assert out["health_on"]["wall_s"] > 0
    assert out["collector"]["workers_seen"] == 2
    assert out["collector"]["reports_ingested"] >= 2
    assert out["collector"]["tracked_series"] >= 1
    assert out["acceptance"]["fleet_covered"] is True
    assert out["acceptance"]["reports_ok"] is True


@pytest.mark.slow  # ~10-70s of bench machinery; the full suite runs it
def test_moe_acceptance_block_shape():
    """The issue-2 tripwire block: booleans (or None off-TPU) with the
    targets recorded next to them, derived from top1 + the sweep."""
    import numpy as _np
    if not hasattr(__import__("jax"), "shard_map"):
        import pytest
        pytest.skip("jax.shard_map unavailable (moe perf legs need it)")
    out = bench._bench_moe(batch=1, seq_len=16, model_dim=16, num_heads=2,
                           num_layers=1, vocab=64, experts=4, reps=1,
                           sweep_layers=1, sweep_steps=8,
                           capacity_factors=(2.0,))
    acc = out["acceptance"]
    assert acc["mfu_target"] == 0.45 and acc["dispatch_pct_target"] == 20.0
    assert acc["trained_drop_target"] == 0.05
    assert acc["dispatch_pct_ok"] is True  # sorted path: 0% dispatch FLOPs
    assert out["top1"]["dispatch_impl"] == "sorted"
    assert out["top1_dense"]["dispatch_impl"] == "dense"
    assert out["top1_dense"]["dispatch_flops_pct"] > 0
    assert _np.isfinite(out["sorted_vs_dense_top1"])


def test_native_features_acceptance_block_tripwires():
    """The ISSUE-11 per-leg tripwires: native per-window wall must be
    at-or-under the Python hub's, None-degrading (the PR-3 convention)
    when either leg is missing, errored, or zero."""
    out = {
        "sparse_python": {"per_window_wall_ms": 40.0},
        "sparse_native": {"per_window_wall_ms": 30.0},
        "adaptive_python": {"per_window_wall_ms": 50.0},
        "adaptive_native": {"per_window_wall_ms": 55.0},
        "sparse_adaptive_python": {"error": "RuntimeError: boom"},
        "sparse_adaptive_native": {"per_window_wall_ms": 30.0},
    }
    bench._native_features_acceptance(out)
    acc = out["acceptance"]
    assert acc["sparse_native_vs_python"] == 0.75
    assert acc["sparse_native_beats_python_ok"] is True
    assert acc["adaptive_native_vs_python"] == 1.1
    assert acc["adaptive_native_beats_python_ok"] is False
    assert acc["sparse_adaptive_native_vs_python"] is None
    assert acc["sparse_adaptive_native_beats_python_ok"] is None

    # zero / missing denominators degrade to None, never ZeroDivision
    out2 = {"sparse_python": {"per_window_wall_ms": 0.0},
            "sparse_native": {"per_window_wall_ms": 1.0}}
    bench._native_features_acceptance(out2)
    assert out2["acceptance"]["sparse_native_beats_python_ok"] is None
    out3 = {}
    bench._native_features_acceptance(out3)
    assert out3["acceptance"]["adaptive_native_beats_python_ok"] is None


@pytest.mark.slow  # ~60-200s of real bench machinery on CPU
def test_bench_async_native_features_tiny_e2e():
    """The ISSUE-11 legs run end to end tiny: every feature combination
    lands a wall number on BOTH hubs (or a recorded error, never a
    crash), and the acceptance block carries one tripwire per leg."""
    from distkeras_tpu.runtime.native import native_available

    out = bench._bench_async_native_features(
        workers=2, window=2, batch=8, windows_per_epoch=2, epochs=1,
        rows=32, dim=4, fields=2)
    acc = out["acceptance"]
    for leg in ("sparse", "adaptive", "sparse_adaptive"):
        for hub in ("python", "native"):
            rec = out[f"{leg}_{hub}"]
            assert isinstance(rec, dict)
            assert "per_window_wall_ms" in rec or "error" in rec
        assert f"{leg}_native_beats_python_ok" in acc
        if native_available():
            # tiny-shape wall is noisy — the tripwire may be False here
            # (the real bench runs production shapes), but it must EXIST
            assert acc[f"{leg}_native_vs_python"] is not None
