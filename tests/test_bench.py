"""bench.py contract: exactly one parseable JSON line on stdout, always.

Round-1 failure mode (VERDICT weak #2): a transient TPU-init error aborted
the bench with rc=1 and zero output, leaving the round with no perf
evidence.  The contract now is: main() never raises, and always prints one
JSON object with the headline metric keys — populated on success, zeroed
with an ``error`` note on failure.
"""

import json

import bench


def _parse_single_json_line(capsys):
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, f"expected exactly one stdout line, got {out}"
    return json.loads(out[0])


def test_main_emits_metric_line(capsys, monkeypatch):
    monkeypatch.setattr(bench, "_bench_mnist_cnn",
                        lambda **kw: 123.4)
    bench.main()
    rec = _parse_single_json_line(capsys)
    assert rec["metric"] == "mnist_cnn_train_samples_per_sec_per_chip"
    assert rec["value"] == 123.4
    assert rec["unit"] == "samples/sec/chip"
    assert isinstance(rec["vs_baseline"], float)
    assert rec["platform"] == "cpu"  # conftest pins the CPU platform


def test_main_emits_diagnostic_line_on_failure(capsys, monkeypatch):
    def boom(**kw):
        raise RuntimeError("synthetic backend meltdown")

    monkeypatch.setattr(bench, "_bench_mnist_cnn", boom)
    bench.main()
    rec = _parse_single_json_line(capsys)
    assert rec["value"] == 0.0 and rec["vs_baseline"] == 0.0
    assert "synthetic backend meltdown" in rec["error"]
    assert rec["metric"] == "mnist_cnn_train_samples_per_sec_per_chip"


def test_mnist_bench_runs_on_cpu():
    sps = bench._bench_mnist_cnn(batch_size=8, num_batches=2, reps=1)
    assert sps > 0


def test_peak_flops_lookup():
    assert bench._peak_flops("TPU v5 lite") == 197e12
    assert bench._peak_flops("TPU v5p chip") == 459e12
    assert bench._peak_flops("Quantum Abacus 9000") is None
