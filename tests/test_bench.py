"""bench.py contract: exactly one parseable JSON line on stdout, always.

Round-1 failure mode (VERDICT weak #2): a transient TPU-init error aborted
the bench with rc=1 and zero output, leaving the round with no perf
evidence.  The contract now is: main() never raises, and always prints one
JSON object with the headline metric keys — populated on success, zeroed
with an ``error`` note on failure.
"""

import json

import bench


def _parse_single_json_line(capsys):
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, f"expected exactly one stdout line, got {out}"
    return json.loads(out[0])


def test_main_emits_metric_line(capsys, monkeypatch):
    monkeypatch.setattr(bench, "_bench_mnist_cnn",
                        lambda **kw: (123.4, bench._METHODOLOGY))
    bench.main()
    rec = _parse_single_json_line(capsys)
    assert rec["metric"] == "mnist_cnn_train_samples_per_sec_per_chip"
    assert rec["value"] == 123.4
    assert rec["unit"] == "samples/sec/chip"
    assert isinstance(rec["vs_baseline"], float)
    assert rec["platform"] == "cpu"  # conftest pins the CPU platform


def test_main_emits_diagnostic_line_on_failure(capsys, monkeypatch):
    def boom(**kw):
        raise RuntimeError("synthetic backend meltdown")

    monkeypatch.setattr(bench, "_bench_mnist_cnn", boom)
    bench.main()
    rec = _parse_single_json_line(capsys)
    assert rec["value"] == 0.0 and rec["vs_baseline"] == 0.0
    assert "synthetic backend meltdown" in rec["error"]
    assert rec["metric"] == "mnist_cnn_train_samples_per_sec_per_chip"


def test_mnist_bench_runs_on_cpu():
    sps, method = bench._bench_mnist_cnn(batch_size=8, num_batches=2, reps=1)
    assert sps > 0
    # the profiler trace has no device module events on CPU: the tag must
    # say WALL so the ratio logic refuses a device-keyed baseline
    assert method == bench._METHODOLOGY_WALL


def test_peak_flops_lookup():
    assert bench._peak_flops("TPU v5 lite") == 197e12
    assert bench._peak_flops("TPU v5p chip") == 459e12
    assert bench._peak_flops("Quantum Abacus 9000") is None


def test_decode_bench_runs_tiny_on_cpu():
    """The decode section (incl. the TRAINED speculative leg) at toy scale:
    every leg present, spread recorded, acceptance_rate a real fraction."""
    out = bench._bench_decode(batch=2, prompt_len=8, new_tokens=16,
                              model_dim=32, num_heads=2, num_layers=2,
                              vocab=64, reps=2, train_steps=8)
    for mode in ("fp", "int8", "fp_b1", "fp_b1_trained", "speculative_b1",
                 "speculative_batched"):
        assert out[mode]["tokens_per_sec"] > 0, mode
        assert "wall_spread" in out[mode], mode
    for sp in (out["speculative_b1"], out["speculative_batched"]):
        assert sp["trained"] is True
        assert 0.0 <= sp["acceptance_rate"] <= 1.0
    assert out["speculative_speedup_vs_fp_batched"] > 0
    # CPU trace may or may not yield module events; the tag must say which
    assert out["timing"] in ("device-median-of-2", "wall-median-of-2")
    assert out["speculative_speedup_vs_fp_b1"] > 0


def test_ring_bench_runs_tiny_on_cpu():
    leg = bench._bench_ring(256, batch=1, heads=2, head_dim=64, steps=1)
    assert leg["l_local"] == 256
    assert leg["flash_ms"] > 0 and leg["dense_ms"] > 0
    assert leg["auto_selects"] == "dense"
    assert leg["timing"] in ("device", "wall")


def test_lm_leg_baseline_keys_include_heads():
    """A heads change must break the baseline match (no bogus ratio)."""
    out = {"lm": [{"seq_len": 2048, "batch": 8, "model_dim": 512,
                   "num_heads": 4, "timing": "device",
                   "tokens_per_sec": 100.0}]}
    baseline = {"legs": {"lm:2048x8:d512h8": {"tokens_per_sec": 50.0}}}
    bench._apply_leg_baselines(out, baseline)
    assert "vs_baseline" not in out["lm"][0]
    out["lm"][0]["num_heads"] = 8
    bench._apply_leg_baselines(out, baseline)
    assert out["lm"][0]["vs_baseline"] == 2.0


def test_ring_baseline_ratio_inverted():
    leg = {"l_local": 2048, "batch": 1, "heads": 8, "head_dim": 64,
           "flash_ms": 2.0, "timing": "device"}
    out = {"ring": [dict(leg)]}
    baseline = {"legs": {"ring:2048:b1h8d64:device": {"flash_ms": 4.0}}}
    bench._apply_leg_baselines(out, baseline)
    assert out["ring"][0]["vs_baseline"] == 2.0  # faster than recorded best

    # a wall-fallback leg must NOT ratio against the device record
    wall = {"ring": [dict(leg, timing="wall")]}
    bench._apply_leg_baselines(wall, baseline)
    assert "vs_baseline" not in wall["ring"][0]

    # a config change (different heads) must break the match
    other = {"ring": [dict(leg, heads=4)]}
    bench._apply_leg_baselines(other, baseline)
    assert "vs_baseline" not in other["ring"][0]


def test_lm_wall_fallback_skips_baseline():
    out = {"lm": [{"seq_len": 2048, "batch": 8, "model_dim": 512,
                   "num_heads": 8, "timing": "wall", "tokens_per_sec": 100.0}]}
    baseline = {"legs": {"lm:2048x8:d512h8": {"tokens_per_sec": 50.0}}}
    bench._apply_leg_baselines(out, baseline)
    assert "vs_baseline" not in out["lm"][0]
