"""Checkpoint/resume tests — the aux subsystem the reference never had
(SURVEY.md §5): atomic no-pickle persistence of full training state, and
bit-exact resume of interrupted training."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.checkpoint import Checkpointer, restore_tree, save_tree


def tree_equal(a, b):
    import jax

    leaves_a = jax.tree.leaves(a)
    leaves_b = jax.tree.leaves(b)
    assert len(leaves_a) == len(leaves_b)
    for x, y in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip(tmp_path):
    tree = {
        "dense": {"kernel": np.arange(12, dtype=np.float32).reshape(3, 4),
                  "bias": np.zeros(4, np.float32)},
        "step": np.int32(7),
        "bf16": jnp.ones((8,), jnp.bfloat16) * 1.5,
    }
    p = str(tmp_path / "tree")
    save_tree(p, tree)
    template = {
        "dense": {"kernel": np.zeros((3, 4), np.float32), "bias": np.zeros(4, np.float32)},
        "step": np.int32(0),
        "bf16": jnp.zeros((8,), jnp.bfloat16),
    }
    restored = restore_tree(p, template)
    tree_equal(tree, restored)
    assert restored["bf16"].dtype == jnp.bfloat16


def test_restore_structure_mismatch_raises(tmp_path):
    p = str(tmp_path / "tree")
    save_tree(p, {"a": np.zeros(3)})
    with pytest.raises(ValueError, match="structure mismatch"):
        restore_tree(p, {"b": np.zeros(3)})
    with pytest.raises(ValueError, match="shape"):
        restore_tree(p, {"a": np.zeros(4)})


def test_checkpointer_retention_and_latest(tmp_path):
    ckpt = Checkpointer(str(tmp_path), keep=2)
    for step in [1, 2, 3, 4]:
        ckpt.save(step, {"t": {"x": np.full(2, step, np.float32)}}, metadata={"epochs_done": step})
    assert ckpt.all_steps() == [3, 4]
    assert ckpt.latest_step() == 4
    out = ckpt.restore({"t": {"x": np.zeros(2, np.float32)}})
    np.testing.assert_array_equal(out["t"]["x"], np.full(2, 4.0))
    assert ckpt.metadata()["metadata"]["epochs_done"] == 4
    # no tmp dirs left behind
    assert not [n for n in os.listdir(tmp_path) if n.startswith(".tmp")]


def test_single_trainer_resume_bit_exact(tmp_path, toy_dataset):
    """1 epoch + resume for the 2nd == 2 epochs straight, to the bit."""
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.trainers import SingleTrainer

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2}, input_shape=(8,))

    def make(num_epoch):
        return SingleTrainer(Model.init(spec, seed=0), loss="categorical_crossentropy",
                             batch_size=64, num_epoch=num_epoch, seed=3)

    t_straight = make(2)
    straight = t_straight.train(toy_dataset)

    ckpt_dir = str(tmp_path / "ck")
    make(1).train(toy_dataset, checkpointer=Checkpointer(ckpt_dir))
    t2 = make(2)
    resumed = t2.train(toy_dataset, checkpointer=Checkpointer(ckpt_dir))
    tree_equal(straight.params, resumed.params)
    # resume skipped epoch 0: history holds exactly the 2nd epoch's batches
    assert len(t2.history) * 2 == len(t_straight.history)


def test_distributed_trainer_resume_bit_exact(tmp_path, toy_dataset):
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.trainers import ADAG

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2}, input_shape=(8,))

    def make(num_epoch):
        return ADAG(Model.init(spec, seed=0), loss="categorical_crossentropy",
                    batch_size=16, num_epoch=num_epoch, num_workers=4,
                    communication_window=2, seed=3)

    straight = make(2).train(toy_dataset)
    ckpt_dir = str(tmp_path / "ck")
    make(1).train(toy_dataset, checkpointer=Checkpointer(ckpt_dir))
    resumed = make(2).train(toy_dataset, checkpointer=Checkpointer(ckpt_dir))
    tree_equal(straight.params, resumed.params)


# -- corrupt-snapshot hardening (issue 4 satellite) ----------------------------

def _save_steps(tmp_path, steps, keep=5):
    ckpt = Checkpointer(str(tmp_path), keep=keep)
    for step in steps:
        ckpt.save(step, {"t": {"x": np.full(2, step, np.float32)}},
                  metadata={"step": step})
    return ckpt


def _corrupt(tmp_path, step, how):
    d = os.path.join(str(tmp_path), f"step_{step:010d}")
    if how == "npz":
        with open(os.path.join(d, "t.npz"), "wb") as f:
            f.write(b"definitely not a zipfile")
    elif how == "meta":
        with open(os.path.join(d, "checkpoint.json"), "w") as f:
            f.write("{ torn json")
    elif how == "missing":
        os.remove(os.path.join(d, "t.npz"))


@pytest.mark.parametrize("how", ["npz", "meta", "missing"])
def test_restore_skips_corrupt_latest_with_warning(tmp_path, how):
    """A torn latest checkpoint (truncated npz, torn manifest, missing
    member — corruption the atomic rename cannot defend against) is
    skipped with a warning and the previous good one is restored."""
    ckpt = _save_steps(tmp_path, [1, 2])
    _corrupt(tmp_path, 2, how)
    with pytest.warns(UserWarning, match="skipping corrupt"):
        out = ckpt.restore({"t": {"x": np.zeros(2, np.float32)}})
    np.testing.assert_array_equal(out["t"]["x"], np.full(2, 1.0))


def test_restore_explicit_corrupt_step_raises(tmp_path):
    """Naming a step explicitly must NOT silently substitute an older one."""
    ckpt = _save_steps(tmp_path, [1, 2])
    _corrupt(tmp_path, 2, "npz")
    with pytest.raises(Exception):
        ckpt.restore({"t": {"x": np.zeros(2, np.float32)}}, step=2)
    # the latest-path still degrades gracefully afterwards
    with pytest.warns(UserWarning):
        out = ckpt.restore({"t": {"x": np.zeros(2, np.float32)}})
    np.testing.assert_array_equal(out["t"]["x"], np.full(2, 1.0))


def test_restore_all_corrupt_raises_with_cause(tmp_path):
    ckpt = _save_steps(tmp_path, [1, 2])
    _corrupt(tmp_path, 1, "npz")
    _corrupt(tmp_path, 2, "meta")
    with pytest.warns(UserWarning):
        with pytest.raises(FileNotFoundError, match="all corrupt"):
            ckpt.restore({"t": {"x": np.zeros(2, np.float32)}})


def test_retention_still_applies_around_corrupt_steps(tmp_path):
    """Retention is by step order, corrupt or not: saving past keep evicts
    the oldest (including a corrupt one) and the survivors stay loadable."""
    ckpt = Checkpointer(str(tmp_path), keep=2)
    for step in [1, 2, 3]:
        ckpt.save(step, {"t": {"x": np.full(2, step, np.float32)}})
    _corrupt(tmp_path, 3, "npz")
    ckpt.save(4, {"t": {"x": np.full(2, 4.0, np.float32)}})
    assert ckpt.all_steps() == [3, 4]
    out = ckpt.restore({"t": {"x": np.zeros(2, np.float32)}})
    np.testing.assert_array_equal(out["t"]["x"], np.full(2, 4.0))
    # kill the good latest too: fallback crosses the corrupt step-3
    _corrupt(tmp_path, 4, "npz")
    with pytest.warns(UserWarning):
        with pytest.raises(FileNotFoundError):
            ckpt.restore({"t": {"x": np.zeros(2, np.float32)}})
