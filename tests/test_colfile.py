"""DKCOL native columnar loader (native/data_loader.cpp + data/colfile.py)."""

import numpy as np
import pytest

from distkeras_tpu.data.colfile import (
    ColumnFile, native_loader_available, write_columns)


needs_native = pytest.mark.skipif(
    not native_loader_available(),
    reason="no C++ toolchain: native loader unavailable (fallback tests still run)")


@pytest.fixture()
def colfile(tmp_path):
    rng = np.random.default_rng(0)
    cols = {
        "features": rng.normal(size=(256, 12)).astype(np.float32),
        "label": np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=256)],
        "label_index": rng.integers(0, 4, size=256).astype(np.int32),
    }
    path = str(tmp_path / "train.dkcol")
    write_columns(path, cols)
    return path, cols


@needs_native
def test_native_loader_builds():
    assert native_loader_available(), "g++ toolchain present but loader failed to build"


@needs_native
def test_roundtrip_native(colfile):
    path, cols = colfile
    with ColumnFile(path) as cf:
        assert cf.native
        assert sorted(cf.columns) == sorted(cols)
        for name, arr in cols.items():
            np.testing.assert_array_equal(cf[name], arr)
            assert cf[name].dtype == arr.dtype


def test_roundtrip_fallback_memmap(colfile, monkeypatch):
    import distkeras_tpu.data.colfile as cfm

    path, cols = colfile
    monkeypatch.setattr(cfm, "_load_lib", lambda: None)
    cf = ColumnFile(path)
    assert not cf.native
    for name, arr in cols.items():
        np.testing.assert_array_equal(cf[name], arr)


@needs_native
def test_views_are_zero_copy(colfile):
    path, _ = colfile
    with ColumnFile(path) as cf:
        arr = cf["features"]
        assert not arr.flags.owndata  # a view over the mapping, not a copy
        assert not arr.flags.writeable


@needs_native
def test_prefetch_and_warm(colfile):
    path, cols = colfile
    with ColumnFile(path, warm=True) as cf:
        cf.prefetch("features", 0, 128)      # madvise path exercised
        cf.prefetch("features", 200, 56)
        cf.prefetch("features", 0, 10**9)    # out-of-range: silently ignored
        import time

        deadline = time.time() + 5
        while cf.warmed_bytes() == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert cf.warmed_bytes() > 0


def test_dataset_and_training_from_file(colfile):
    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.trainers import SingleTrainer

    path, cols = colfile
    with ColumnFile(path) as cf:
        ds = cf.dataset()
        assert len(ds) == 256
        # chunked feeding straight off the mapping
        chunks = list(ds.chunked_epoch(32, ["features", "label"], chunk_windows=4))
        assert sum(c["features"].shape[0] for c in chunks) == 8
        spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 4},
                         input_shape=(12,))
        t = SingleTrainer(spec, batch_size=32, num_epoch=2, learning_rate=0.1)
        model = t.train(ds)
        assert np.isfinite(t.history).all()


def test_corrupt_file_rejected(tmp_path):
    bad = tmp_path / "bad.dkcol"
    bad.write_bytes(b"NOTDKCOL" + b"\x00" * 64)
    with pytest.raises(OSError, match="magic|DKCOL"):
        ColumnFile(str(bad))


def test_chunked_epoch_prefetches_ahead(colfile, monkeypatch):
    path, _ = colfile
    with ColumnFile(path) as cf:
        calls = []
        monkeypatch.setattr(cf, "prefetch",
                            lambda name, start, n: calls.append((name, start, n)))
        ds = cf.dataset()
        chunks = list(ds.chunked_epoch(32, ["features"], chunk_windows=3))
        assert len(chunks) == 3  # 8 windows -> 3 + 3 + 2
        # while chunk 0 is out, chunk 1's rows were advised; ditto chunk 2
        assert ("features", 3 * 32, 3 * 32) in calls
        assert ("features", 6 * 32, 2 * 32) in calls


@needs_native
def test_views_survive_close(colfile):
    """Mapping outlives close(): views handed out earlier must stay valid
    (release semantics — no munmap under live numpy views)."""
    path, cols = colfile
    cf = ColumnFile(path)
    ds = cf.dataset()
    arr = cf["features"]
    cf.close()
    np.testing.assert_array_equal(arr, cols["features"])  # would SIGSEGV pre-fix
    np.testing.assert_array_equal(ds["label_index"], cols["label_index"])


def test_chunk_local_shuffle(colfile):
    path, cols = colfile
    with ColumnFile(path) as cf:
        ds = cf.dataset().shuffle(seed=7)
        chunks = list(ds.chunked_epoch(32, ["features", "label_index"], chunk_windows=4))
        feats = np.concatenate([c["features"].reshape(-1, 12) for c in chunks])
        labels = np.concatenate([c["label_index"].reshape(-1) for c in chunks])
        # all rows present exactly once, order changed, feature/label pairing kept
        order = np.lexsort(feats.T)
        ref_order = np.lexsort(cols["features"].T)
        np.testing.assert_array_equal(feats[order], cols["features"][ref_order])
        assert not np.array_equal(feats, cols["features"])
        for f, l in zip(feats[:32], labels[:32]):
            idx = np.where((cols["features"] == f).all(axis=1))[0]
            assert len(idx) == 1 and cols["label_index"][idx[0]] == l


def test_split_rejected_on_mapped_dataset(colfile):
    path, _ = colfile
    with ColumnFile(path) as cf:
        with pytest.raises(NotImplementedError, match="write separate"):
            cf.dataset().split(0.9, seed=0)


@pytest.mark.parametrize("force_fallback", [False, True],
                         ids=["native", "fallback"])
def test_corrupt_offset_overflow_rejected(tmp_path, monkeypatch, force_fallback):
    import struct

    if force_fallback:
        import distkeras_tpu.data.colfile as cfm

        monkeypatch.setattr(cfm, "_load_lib", lambda: None)
    elif not native_loader_available():
        pytest.skip("no C++ toolchain")
    # hand-craft a header whose offset+nbytes wraps uint64
    path = tmp_path / "evil.dkcol"
    name, dtype = b"x", b"<f4"
    header = struct.pack("<I", 1)
    header += struct.pack("<I", len(name)) + name
    header += struct.pack("<I", len(dtype)) + dtype
    header += struct.pack("<I", 1) + struct.pack("<q", 4)
    header += struct.pack("<QQ", 0xFFFFFFFFFFFFF000, 0x2000)
    path.write_bytes(b"DKCOL1\0\0" + header + b"\x00" * 64)
    with pytest.raises(OSError, match="corrupt"):
        ColumnFile(str(path))


def test_prefetch_to_device_order_and_lookahead():
    """The double-buffered feed yields every chunk in order and issues
    each placement one chunk AHEAD of consumption."""
    from distkeras_tpu.data.dataset import prefetch_to_device

    events = []

    def chunks():
        for i in range(4):
            events.append(("produce", i))
            yield i

    def place(i):
        events.append(("place", i))
        return i * 10

    out = []
    for v in prefetch_to_device(chunks(), place):
        events.append(("consume", v // 10))
        out.append(v)
    assert out == [0, 10, 20, 30]
    # chunk 1 was produced AND placed before chunk 0 was consumed
    assert events.index(("place", 1)) < events.index(("consume", 0))
    # and the empty iterator is a clean no-op
    assert list(prefetch_to_device(iter(()), place)) == []


def test_prefetch_producer_exits_on_abandoned_consumer():
    """An exception (or early break) mid-epoch abandons the prefetch
    generator; the background producer must notice and exit instead of
    blocking in q.put forever (a leaked thread + chunk per retry)."""
    import gc
    import threading
    import time

    from distkeras_tpu.data.dataset import prefetch_to_device

    base = threading.active_count()

    def endless():
        i = 0
        while True:
            yield i
            i += 1

    it = prefetch_to_device(endless(), lambda c: c)
    assert next(it) == 0
    it.close()  # consumer abandons mid-epoch
    deadline = time.time() + 5
    while threading.active_count() > base and time.time() < deadline:
        gc.collect()  # the inner generator's finally runs on collection
        time.sleep(0.05)
    assert threading.active_count() <= base, "producer thread leaked"


def test_out_of_core_epoch_bounded_anonymous_memory(tmp_path):
    """Train through a ColumnFile LARGER than the bounded feed chunks and
    assert the process's ANONYMOUS memory (heap + device buffers on the
    CPU backend — what a full in-RAM materialization would grow) stays
    well under the file size.  File-backed mapped pages are excluded on
    purpose: the epoch legitimately touches every page of the mapping;
    the out-of-core claim is that nothing COPIES the dataset."""
    import threading

    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.trainers import SingleTrainer

    def rss_anon_kb():
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("RssAnon"):
                    return int(line.split()[1])
        return 0  # pragma: no cover - non-Linux

    rows, feat = 16384, 1024  # 64MB of f32 features
    rng = np.random.default_rng(0)
    path = str(tmp_path / "big.dkcol")
    write_columns(path, {
        "features": rng.normal(size=(rows, feat)).astype(np.float32),
        "label": np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=rows)],
    })
    file_mb = 64
    import gc

    # warm the JAX runtime + compile the trainer's epoch program BEFORE the
    # baseline sample: first-compile anonymous memory (~tens of MB) must
    # not be attributed to the feed path (the test would otherwise be
    # order-dependent — failing when run alone, passing after earlier
    # tests warm the runtime)
    from distkeras_tpu.data.dataset import Dataset

    warm_rng = np.random.default_rng(1)
    warm_ds = Dataset({"features": warm_rng.normal(size=(512, feat)).astype(np.float32),
                       "label": np.eye(4, dtype=np.float32)[warm_rng.integers(0, 4, 512)]})
    SingleTrainer(ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 4},
                            input_shape=(feat,)),
                  batch_size=64, num_epoch=1, learning_rate=0.1,
                  chunk_windows=8).train(warm_ds, shuffle=True)
    del warm_ds
    gc.collect()
    base_kb = rss_anon_kb()
    peak = [base_kb]
    stop = threading.Event()

    def sample():
        while not stop.wait(0.005):
            peak[0] = max(peak[0], rss_anon_kb())

    t = threading.Thread(target=sample, daemon=True)
    t.start()
    try:
        with ColumnFile(path) as cf:
            spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 4},
                             input_shape=(feat,))
            # chunk_windows=8 at batch 64 -> 2MB chunks; two in flight
            tr = SingleTrainer(spec, batch_size=64, num_epoch=1,
                               learning_rate=0.1, chunk_windows=8)
            tr.train(cf.dataset(), shuffle=True)
    finally:
        stop.set()
        t.join(timeout=5)
    grew_mb = (peak[0] - base_kb) / 1024
    assert np.isfinite(tr.history).all()
    # a full materialization (or global shuffle copy) would add >= 64MB of
    # anonymous memory; the bounded feed should stay far under half that
    # even with compile + double-buffered chunks
    assert grew_mb < file_mb / 2, f"anonymous memory grew {grew_mb:.1f}MB"
