"""Dataset + transformer tests (reference behaviors from SURVEY §2.16)."""

import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.transformers import (
    DenseTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    OneHotTransformer,
    ReshapeTransformer,
)


def make_ds(n=20):
    return Dataset({
        "features": np.arange(n * 4, dtype=np.float32).reshape(n, 4),
        "label": np.arange(n, dtype=np.int32) % 3,
    })


def test_dataset_basics():
    ds = make_ds()
    assert len(ds) == 20
    assert set(ds.columns) == {"features", "label"}
    taken = ds.take(5)
    assert len(taken) == 5


def test_dataset_rejects_ragged_columns():
    with pytest.raises(ValueError):
        Dataset({"a": np.zeros(3), "b": np.zeros(4)})


def test_batches_drop_remainder():
    ds = make_ds(n=10)
    batches = list(ds.batches(3))
    assert len(batches) == 3
    assert all(b["features"].shape == (3, 4) for b in batches)


def test_stacked_epoch_shapes():
    ds = make_ds(n=20)
    stacked = ds.stacked_epoch(batch_size=2, columns=["features"], window=2)
    assert stacked["features"].shape == (5, 2, 2, 4)


def test_split():
    ds = make_ds(n=20)
    train, test = ds.split(0.75, seed=0)
    assert len(train) == 15 and len(test) == 5


def test_onehot_transformer():
    ds = make_ds()
    out = OneHotTransformer(3, input_col="label", output_col="onehot").transform(ds)
    onehot = out["onehot"]
    assert onehot.shape == (20, 3)
    np.testing.assert_array_equal(np.argmax(onehot, axis=1), ds["label"])
    np.testing.assert_allclose(onehot.sum(axis=1), 1.0)


def test_minmax_transformer():
    ds = Dataset({"features": np.array([[0.0], [127.5], [255.0]], dtype=np.float32)})
    out = MinMaxTransformer(0.0, 1.0, 0.0, 255.0, "features", "scaled").transform(ds)
    np.testing.assert_allclose(out["scaled"], [[0.0], [0.5], [1.0]], atol=1e-6)


def test_reshape_transformer():
    ds = Dataset({"flat": np.zeros((6, 12), dtype=np.float32)})
    out = ReshapeTransformer("flat", "img", (2, 3, 2)).transform(ds)
    assert out["img"].shape == (6, 2, 3, 2)


def test_dense_transformer():
    indices = np.array([[0, 2, -1], [1, -1, -1]], dtype=np.int32)
    values = np.array([[1.0, 3.0, 0.0], [5.0, 0.0, 0.0]], dtype=np.float32)
    ds = Dataset({"indices": indices, "values": values})
    out = DenseTransformer(size=4).transform(ds)
    np.testing.assert_allclose(out["features"], [[1, 0, 3, 0], [0, 5, 0, 0]])


def test_label_index_transformer():
    preds = np.array([[0.1, 0.8, 0.1], [0.9, 0.05, 0.05]], dtype=np.float32)
    ds = Dataset({"prediction": preds})
    out = LabelIndexTransformer(3).transform(ds)
    np.testing.assert_array_equal(out["prediction_index"], [1, 0])


def test_chunk_windows_for_budget():
    """Budget helper (feed-bench promoted default): chunks sized near the
    byte budget, floored at one window, loud on nonsense inputs."""
    from distkeras_tpu.data.dataset import (DEFAULT_CHUNK_BUDGET_BYTES,
                                            chunk_windows_for_budget)

    # 1 KB rows, batch 32, window 1 -> budget//32KB windows
    assert chunk_windows_for_budget(1024, 32, 1) == \
        DEFAULT_CHUNK_BUDGET_BYTES // (1024 * 32)
    # explicit budget override
    assert chunk_windows_for_budget(1024, 32, 1, budget_bytes=64 * 1024) == 2
    # a single window can exceed the budget; never returns 0
    assert chunk_windows_for_budget(10**9, 32, 1) == 1
    with pytest.raises(ValueError):
        chunk_windows_for_budget(0, 32, 1)
    with pytest.raises(ValueError):
        chunk_windows_for_budget(1024, 0, 1)


def test_trainer_auto_chunk_windows(tmp_path):
    """chunk_windows="auto" resolves per dataset via the budget helper and
    the trainer still learns through the chunked feed."""
    from distkeras_tpu.data.dataset import DEFAULT_CHUNK_BUDGET_BYTES
    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.trainers import SingleTrainer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(axis=1) > 0).astype(np.int32)]
    ds = Dataset({"features": x, "label": y})
    spec = ModelSpec(name="mlp",
                     config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    tr = SingleTrainer(spec, loss="categorical_crossentropy", batch_size=32,
                       num_epoch=3, learning_rate=0.1, chunk_windows="auto")
    # resolution: 32-byte rows x batch 32 = 1KB/window; budget >> dataset,
    # so auto resolves to a large step and chunked_epoch caps it at the
    # epoch — the small-data case degrades to the fast path by arithmetic
    resolved = tr._resolve_chunk_windows(ds, 32, 1)
    assert resolved == DEFAULT_CHUNK_BUDGET_BYTES // (8 * 4 * 32)
    model = tr.train(ds)
    assert tr.history[-1] < tr.history[0]
    assert model.predict(x[:4]).shape == (4, 2)
