"""KV-cache decoding: parity with the training-path forward and the
semantics of generation (greedy, EOS padding, sampling, guards)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models.base import Model
from distkeras_tpu.models.decode import (forward_with_cache, generate,
                                         init_cache, make_generate_fn)
from distkeras_tpu.models.transformer import small_lm_spec


def _spec(**kw):
    # float32 compute so parity tolerances are tight (bf16 would add
    # rounding noise between the einsum and flax Dense formulations)
    cfg = dict(vocab_size=61, model_dim=32, num_heads=2, num_layers=2,
               max_seq_len=32)
    cfg.update(kw)
    spec = small_lm_spec(**cfg)
    spec.config["compute_dtype"] = "float32"
    return spec


@pytest.fixture(scope="module")
def model():
    return Model.init(_spec(), seed=0)


def test_prefill_logits_match_training_forward(model):
    """forward_with_cache at start_pos=0 must reproduce the Flax module's
    logits exactly (same math, different formulation)."""
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 61, (2, 9)))
    want = model.apply(toks)
    cache = init_cache(model.spec.config, 2, 16)
    got, cache2 = forward_with_cache(model.params, model.spec.config, toks, 0, cache)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    # the cache rows beyond the prompt stay zero (dead until written)
    assert np.all(np.asarray(cache2.k[:, :, 9:]) == 0)


@pytest.mark.slow  # tier-1 budget (ISSUE 14 satellite): 7.8 s: whole-sequence incremental parity; the fused/greedy/quantized parity cells keep decode coverage in tier-1
def test_incremental_decode_matches_full_forward(model):
    """Feeding tokens one at a time through the cache must give the same
    last-position logits as re-running the full prefix each time."""
    rng = np.random.default_rng(1)
    toks = jnp.asarray(rng.integers(0, 61, (1, 8)))
    cache = init_cache(model.spec.config, 1, 8)
    logits_p, cache = forward_with_cache(model.params, model.spec.config,
                                         toks[:, :3], 0, cache)
    last = [logits_p[:, -1]]
    for pos in range(3, 8):
        step_logits, cache = forward_with_cache(
            model.params, model.spec.config, toks[:, pos:pos + 1],
            jnp.asarray(pos, jnp.int32), cache)
        last.append(step_logits[:, -1])
    for pos in range(3, 9):
        want = model.apply(toks[:, :pos])[:, -1]
        np.testing.assert_allclose(np.asarray(last[pos - 3]), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


def test_greedy_generate_matches_naive_argmax_loop(model):
    """generate(temperature=0) must equal the O(L^2) loop that re-runs the
    module on the growing sequence and argmaxes the last position."""
    prompt = jnp.asarray([[5, 17, 3], [40, 2, 60]], jnp.int32)
    out = generate(model, prompt, max_new_tokens=6)
    assert out.shape == (2, 6)

    seq = prompt
    for _ in range(6):
        nxt = jnp.argmax(model.apply(seq)[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq[:, 3:]))


def test_eos_rows_pad_after_stopping(model):
    """Find an EOS id the greedy run actually emits, regenerate with it
    declared: the EOS itself is kept, everything after is pad_id."""
    prompt = jnp.asarray([[5, 17, 3]], jnp.int32)
    free = np.asarray(generate(model, prompt, max_new_tokens=6))[0]
    eos = int(free[2])  # declare the 3rd emitted token to be EOS
    out = np.asarray(generate(model, prompt, max_new_tokens=6,
                              eos_id=eos, pad_id=0))[0]
    np.testing.assert_array_equal(out[:3], free[:3])
    assert np.all(out[3:] == 0)


def test_sampled_generation_reproducible_and_in_range(model):
    fn = make_generate_fn(model.spec, 5, temperature=0.8, top_k=10)
    rng = jax.random.PRNGKey(7)
    a = fn(model.params, jnp.zeros((3, 4), jnp.int32), rng)
    b = fn(model.params, jnp.zeros((3, 4), jnp.int32), rng)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (3, 5)
    assert np.all((np.asarray(a) >= 0) & (np.asarray(a) < 61))


def test_generate_rejects_overflow_and_sharded_specs(model):
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, jnp.zeros((1, 30), jnp.int32), max_new_tokens=10)
    sharded = _spec(seq_axis="sp")
    with pytest.raises(ValueError, match="non-sharded"):
        make_generate_fn(sharded, 4)
    moe = _spec(moe_experts=4)
    with pytest.raises(ValueError, match="MoE"):
        make_generate_fn(moe, 4)


def test_oversized_cache_with_short_sequence_is_fine(model):
    """An explicit cache larger than needed (even than max_seq_len's worth
    of live rows) must not be rejected — dead rows are masked."""
    fn = make_generate_fn(model.spec, 4, cache_len=32)
    out = fn(model.params, jnp.asarray([[5, 17, 3]], jnp.int32))
    want = generate(model, jnp.asarray([[5, 17, 3]], jnp.int32), max_new_tokens=4)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))


def test_generate_rejects_undersized_cache(model):
    fn = make_generate_fn(model.spec, 8, cache_len=4)
    with pytest.raises(ValueError, match="cannot hold"):
        fn(model.params, jnp.zeros((1, 3), jnp.int32))


def test_sharded_generate_matches_single_device(model):
    """GSPMD-partitioned decoding ((dp x tp) mesh) must reproduce the
    single-device greedy tokens — the collectives change the schedule,
    not the math (float32 compute keeps argmax ties deterministic)."""
    from distkeras_tpu.models.decode import make_sharded_generate_fn
    from distkeras_tpu.parallel.mesh import create_nd_mesh

    mesh = create_nd_mesh((2, 2), ("dp", "tp"))
    prompt = jnp.asarray([[5, 17, 3], [40, 2, 60]], jnp.int32)
    want = generate(model, prompt, max_new_tokens=6)
    fn = make_sharded_generate_fn(model.spec, mesh, 6, tp_axis="tp", dp_axis="dp")
    got = fn(model.params, prompt)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sharded_generate_rejects_indivisible_heads(model):
    from distkeras_tpu.models.decode import make_sharded_generate_fn
    from distkeras_tpu.parallel.mesh import create_nd_mesh

    mesh = create_nd_mesh((8,), ("tp",))  # model has 2 heads
    with pytest.raises(ValueError, match="not divisible"):
        make_sharded_generate_fn(model.spec, mesh, 4)


def test_sharded_generate_rejects_bad_axis_and_spec(model):
    from distkeras_tpu.models.decode import make_sharded_generate_fn
    from distkeras_tpu.models.sequential import dense, sequential_spec
    from distkeras_tpu.parallel.mesh import create_nd_mesh

    mesh = create_nd_mesh((2, 2), ("dp", "tp"))
    with pytest.raises(ValueError, match="not a mesh axis"):
        make_sharded_generate_fn(model.spec, mesh, 4, tp_axis="model")
    with pytest.raises(ValueError, match="transformer_lm"):
        make_sharded_generate_fn(sequential_spec([dense(4)], input_shape=(3,)),
                                 mesh, 4)


def test_quantized_tree_decodes_and_matches(model):
    """int8 params decode through the same generate fn; greedy tokens stay
    reasonable (exactly equal on this tiny f32 model whose argmax margins
    dwarf int8 error is too strong a claim — check token validity + high
    agreement instead)."""
    from distkeras_tpu.ops.quantize import quantize_params

    prompt = jnp.asarray([[5, 17, 3]], jnp.int32)
    full = np.asarray(generate(model, prompt, max_new_tokens=8))
    qp = quantize_params(model.params, min_size=64)
    fn = make_generate_fn(model.spec, 8)
    q = np.asarray(fn(qp, prompt))
    assert q.shape == full.shape
    assert ((q >= 0) & (q < 61)).all()
    # int8 error is tiny on this f32 model: the greedy path must track the
    # full-precision tokens closely, or the scale broadcasting is wrong
    assert (q == full).mean() >= 0.75, f"int8 tokens diverged: {q} vs {full}"
    from distkeras_tpu.models.decode import make_sharded_generate_fn
    from distkeras_tpu.parallel.mesh import create_nd_mesh

    with pytest.raises(ValueError, match="quantized"):
        make_sharded_generate_fn(model.spec, create_nd_mesh((2,), ("tp",)), 4,
                                 tp_axis="tp")(qp, prompt)


# --- fused Pallas decode step (ops/decode_step.py) -------------------------
# CPU runs the kernel through the Pallas interpreter (auto-selected
# off-TPU), so these pin kernel/XLA parity without hardware; keep the
# token counts small — interpreted kernels are slow.


def _fused_spec(**kw):
    cfg = dict(vocab_size=97, model_dim=128, num_heads=2, num_layers=2,
               max_seq_len=64)
    cfg.update(kw)
    return small_lm_spec(**cfg)


@pytest.fixture(scope="module")
def fused_model():
    return Model.init(_fused_spec(), seed=3)


def test_fused_step_greedy_parity(fused_model):
    """The fused block kernel must emit exactly the XLA step's greedy
    tokens — batch 1 (sublane-padded to 8) and batch 3."""
    rng = np.random.default_rng(0)
    for batch in (1, 3):
        prompt = jnp.asarray(rng.integers(0, 97, (batch, 5)), jnp.int32)
        want = np.asarray(make_generate_fn(fused_model.spec, 8, step_impl="xla")(
            fused_model.params, prompt))
        got = np.asarray(make_generate_fn(fused_model.spec, 8, step_impl="fused")(
            fused_model.params, prompt))
        np.testing.assert_array_equal(got, want, err_msg=f"batch={batch}")


def test_fused_step_eos_padding_parity(fused_model):
    """EOS/pad semantics live outside the kernel and must be unaffected:
    pick an eos id the greedy decode actually emits."""
    prompt = jnp.asarray([[11, 60, 2]], jnp.int32)
    plain = np.asarray(make_generate_fn(fused_model.spec, 6, step_impl="xla")(
        fused_model.params, prompt))
    eos = int(plain[0, 1])
    want = np.asarray(make_generate_fn(fused_model.spec, 6, step_impl="xla",
                                       eos_id=eos, pad_id=7)(
        fused_model.params, prompt))
    got = np.asarray(make_generate_fn(fused_model.spec, 6, step_impl="fused",
                                      eos_id=eos, pad_id=7)(
        fused_model.params, prompt))
    np.testing.assert_array_equal(got, want)


def test_fused_step_int8_tree_parity(fused_model):
    """QTensor leaves dequantize inside stack_decode_weights: the fused
    path must match the XLA path run on the SAME quantized tree."""
    from distkeras_tpu.ops.quantize import quantize_params

    qp = quantize_params(fused_model.params, min_size=64)
    prompt = jnp.asarray([[40, 8]], jnp.int32)
    want = np.asarray(make_generate_fn(fused_model.spec, 6, step_impl="xla")(
        qp, prompt))
    got = np.asarray(make_generate_fn(fused_model.spec, 6, step_impl="fused")(
        qp, prompt))
    np.testing.assert_array_equal(got, want)


def test_fused_step_cache_len_rounds_up(fused_model):
    """A cache_len that is not 128-aligned is rounded up inside the fused
    run (the transposed K slab puts sequence on lanes); dead cache rows
    are masked, so different cache sizes must decode identically.
    (Fused-vs-fused on purpose: an xla-vs-fused check here once tripped
    over a genuine 3e-5 logit near-tie on this random bf16 model —
    cross-impl float noise, not round-up mechanics.)"""
    prompt = jnp.asarray([[9, 9, 10]], jnp.int32)
    want = np.asarray(make_generate_fn(fused_model.spec, 5, step_impl="fused",
                                       cache_len=256)(fused_model.params, prompt))
    got = np.asarray(make_generate_fn(fused_model.spec, 5, step_impl="fused",
                                      cache_len=17)(fused_model.params, prompt))
    np.testing.assert_array_equal(got, want)


def test_fused_step_rejects_unsupported_shapes(model):
    """model_dim 32 is not lane-tiled: explicit step_impl='fused' must
    fail loudly, and auto-select must silently use the XLA step."""
    prompt = jnp.asarray([[1, 2]], jnp.int32)
    with pytest.raises(ValueError, match="fused"):
        make_generate_fn(model.spec, 4, step_impl="fused")(model.params, prompt)
    toks = make_generate_fn(model.spec, 4)(model.params, prompt)  # auto
    assert np.asarray(toks).shape == (1, 4)


# --- nucleus (top-p) sampling ----------------------------------------------


def test_top_p_restricts_support_and_keeps_argmax():
    """Direct _sample checks on a hand-built distribution: the nucleus
    contains exactly the smallest prefix of sorted probs reaching top_p,
    and a tiny top_p degrades to greedy."""
    from distkeras_tpu.models.decode import _sample

    # probs ~ [0.5, 0.25, 0.15, 0.1]: top_p=0.6 keeps {0, 1} (0.5 < 0.6,
    # exclusive-prefix rule), top_p=0.76 keeps {0, 1, 2}
    logits = jnp.log(jnp.asarray([[0.5, 0.25, 0.15, 0.1]], jnp.float32))
    seen = {int(_sample(logits, jax.random.PRNGKey(s), 1.0, 0, 0.6)[0])
            for s in range(200)}
    assert seen == {0, 1}, seen
    seen = {int(_sample(logits, jax.random.PRNGKey(s), 1.0, 0, 0.76)[0])
            for s in range(400)}
    assert seen == {0, 1, 2}, seen
    # nucleus always contains the argmax: top_p -> 0 is greedy
    assert all(int(_sample(logits, jax.random.PRNGKey(s), 1.0, 0, 1e-6)[0]) == 0
               for s in range(20))
    # ties at the nucleus boundary must NOT re-admit every tied token (a
    # probability-threshold cut would keep all 4): uniform probs with
    # top_p=0.3 keep exactly the 2-token prefix whose mass reaches 0.3
    tied = jnp.zeros((1, 4), jnp.float32)
    seen = {int(_sample(tied, jax.random.PRNGKey(s), 1.0, 0, 0.3)[0])
            for s in range(100)}
    assert len(seen) == 2, seen


def test_top_p_out_of_range_rejected(model):
    """A negative top_p would pass the `top_p and top_p < 1.0` gate, mask
    every token (including the argmax) to -inf, and categorical over an
    all--inf row silently emits token 0 — so the builder must reject it
    loudly, like the speculative path's temperature guard."""
    for bad in (-0.1, 1.5, float("nan")):
        with pytest.raises(ValueError, match="top_p"):
            make_generate_fn(model.spec, 4, temperature=1.0, top_p=bad)
    with pytest.raises(ValueError, match="temperature"):
        make_generate_fn(model.spec, 4, temperature=-1.0)
    for bad_k in (-1, 10_000):
        with pytest.raises(ValueError, match="top_k"):
            make_generate_fn(model.spec, 4, temperature=1.0, top_k=bad_k)


def test_undersized_cache_len_rejected_on_both_impls(fused_model):
    """cache_len=100 for prompt 90 + 20 new tokens must raise on BOTH
    step impls: the fused path's lane round-up (100 -> 128) must not
    rescue a capacity the user explicitly undersized (the same call
    erroring or not depending on auto impl selection)."""
    prompt = jnp.zeros((1, 90), jnp.int32)
    for impl in ("xla", "fused"):
        with pytest.raises(ValueError, match="cannot hold"):
            make_generate_fn(fused_model.spec, 20, cache_len=100,
                             step_impl=impl)(fused_model.params, prompt)


def test_generate_with_top_p_reproducible_and_in_range(model):
    toks1 = generate(model, jnp.asarray([[3, 7]], jnp.int32), 8,
                     temperature=0.8, top_p=0.9, seed=5)
    toks2 = generate(model, jnp.asarray([[3, 7]], jnp.int32), 8,
                     temperature=0.8, top_p=0.9, seed=5)
    np.testing.assert_array_equal(np.asarray(toks1), np.asarray(toks2))
    a = np.asarray(toks1)
    assert a.shape == (1, 8) and ((a >= 0) & (a < 61)).all()


# --- int8-quantized KV cache (QKVCache) ------------------------------------


def test_quantized_cache_matches_dequantized_oracle(model, monkeypatch):
    """The int8-cache forward must equal the SAME math over the
    rounded-then-dequantized values.  Oracle = the production code path
    itself, with _quantize_rows faked to store the dequantized f32
    values at scale 1 (int8 in [-127, 127] converts to bf16/f32
    exactly, so the two runs differ only in where the scale multiply
    happens — an exact-to-float-noise identity if the plumbing is
    right)."""
    import distkeras_tpu.models.decode as dec
    from distkeras_tpu.models.decode import QKVCache

    cfg = model.spec.config
    toks = jnp.asarray(np.random.default_rng(3).integers(0, 61, (2, 6)))
    cache_q = init_cache(cfg, 2, 16, quantized=True)
    logits_q, cache_q = forward_with_cache(model.params, cfg, toks, 0, cache_q)
    step_q, _ = forward_with_cache(model.params, cfg,
                                   jnp.asarray([[7], [9]], jnp.int32),
                                   jnp.asarray(6, jnp.int32), cache_q)

    real = dec._quantize_rows

    def fake(x):
        q, s = real(x)
        return q.astype(jnp.float32) * s, jnp.ones_like(s)

    monkeypatch.setattr(dec, "_quantize_rows", fake)
    shape = cache_q.k.shape
    oracle = QKVCache(jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32),
                      jnp.ones(shape[:-1] + (1,), jnp.float32),
                      jnp.ones(shape[:-1] + (1,), jnp.float32))
    logits_o, oracle = forward_with_cache(model.params, cfg, toks, 0, oracle)
    step_o, _ = forward_with_cache(model.params, cfg,
                                   jnp.asarray([[7], [9]], jnp.int32),
                                   jnp.asarray(6, jnp.int32), oracle)
    np.testing.assert_allclose(np.asarray(logits_q), np.asarray(logits_o),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(step_q), np.asarray(step_o),
                               rtol=1e-4, atol=1e-4)


def test_quantized_cache_generation_runs_and_tracks_plain(model):
    """End-to-end generate with quantize_cache: valid tokens, and on
    this tiny f32 model the per-row rounding (<0.8% relative) keeps
    greedy tokens mostly equal to the full-precision decode."""
    prompt = jnp.asarray([[5, 17, 3]], jnp.int32)
    plain = np.asarray(make_generate_fn(model.spec, 8)(model.params, prompt))
    quant = np.asarray(make_generate_fn(model.spec, 8, quantize_cache=True)(
        model.params, prompt))
    assert quant.shape == plain.shape
    assert ((quant >= 0) & (quant < 61)).all()
    assert (quant == plain).mean() >= 0.5, f"{quant} vs {plain}"


def test_quantized_cache_rejects_fused_step(fused_model):
    with pytest.raises(ValueError, match="quantize_cache"):
        make_generate_fn(fused_model.spec, 4, quantize_cache=True,
                         step_impl="fused")


def test_quantized_cache_forces_xla_step_on_tpu_auto(fused_model, monkeypatch):
    """With quantize_cache the auto step selection must resolve to the
    XLA step even where fused_step_auto would fire (TPU, batch 1, small
    model) — the fused kernel's bf16 slabs would silently drop the int8
    scales.  Faking a TPU backend on CPU makes the bug observable: the
    buggy path tries to Mosaic-compile the fused kernel and fails, the
    fixed path decodes through XLA."""
    import distkeras_tpu.ops.decode_step as ds

    monkeypatch.setattr(ds.jax, "default_backend", lambda: "tpu")
    toks = make_generate_fn(fused_model.spec, 5, quantize_cache=True)(
        fused_model.params, jnp.asarray([[8, 2]], jnp.int32))
    assert np.asarray(toks).shape == (1, 5)
