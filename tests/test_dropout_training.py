"""Dropout is REAL in the training paths that plumb rng keys, off at
inference, and loudly refused where no plumbing exists (v1)."""

import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.sequential import dense, dropout, sequential_spec
from distkeras_tpu.trainers import ADAG, SingleTrainer


def _data(n=256, d=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    labels = (x.sum(axis=1) > 0).astype(np.int64)
    return Dataset({"features": x, "label": np.eye(2, dtype=np.float32)[labels]})


def _spec(rate):
    return sequential_spec([dense(64, "relu"), dropout(rate), dense(2)],
                           input_shape=(8,))


def test_single_trainer_dropout_changes_training():
    """rate 0.9 vs 0.0, identical everything else: histories must differ
    (an inert dropout would make them bit-identical)."""
    ds = _data()
    h = {}
    for rate in (0.0, 0.9):
        tr = SingleTrainer(_spec(rate), batch_size=32, num_epoch=2,
                           learning_rate=0.05, seed=3)
        tr.train(ds, shuffle=False)
        h[rate] = np.asarray(tr.history)
    assert np.isfinite(h[0.0]).all() and np.isfinite(h[0.9]).all()
    assert np.abs(h[0.0] - h[0.9]).max() > 0


def test_single_trainer_dropout_deterministic_given_seed():
    ds = _data()
    runs = []
    for _ in range(2):
        tr = SingleTrainer(_spec(0.5), batch_size=32, num_epoch=2,
                           learning_rate=0.05, seed=7)
        m = tr.train(ds, shuffle=False)
        runs.append((np.asarray(tr.history), m))
    np.testing.assert_array_equal(runs[0][0], runs[1][0])
    import jax

    for a, b in zip(jax.tree.leaves(runs[0][1].params),
                    jax.tree.leaves(runs[1][1].params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_distributed_trainer_dropout_trains_and_is_deterministic():
    ds = _data()
    runs = []
    for _ in range(2):
        tr = ADAG(_spec(0.5), num_workers=8, batch_size=8, num_epoch=2,
                  communication_window=2, learning_rate=0.05, seed=1)
        tr.train(ds, shuffle=False)
        runs.append(np.asarray(tr.history))
    assert np.isfinite(runs[0]).all()
    np.testing.assert_array_equal(runs[0], runs[1])
    # and dropout actually bites on the distributed path too
    tr0 = ADAG(_spec(0.0), num_workers=8, batch_size=8, num_epoch=2,
               communication_window=2, learning_rate=0.05, seed=1)
    tr0.train(ds, shuffle=False)
    assert np.abs(np.asarray(tr0.history) - runs[0]).max() > 0


def test_unplumbed_paths_refuse_dropout_specs():
    import optax

    from distkeras_tpu.parallel.mesh import create_mesh
    from distkeras_tpu.parallel.zero import make_zero_train_step
    from distkeras_tpu.ops.losses import get_loss
    from distkeras_tpu.runtime.async_trainer import AsyncDOWNPOUR

    spec = _spec(0.5)
    with pytest.raises(ValueError, match="no PRNG plumbing"):
        make_zero_train_step(spec, get_loss("categorical_crossentropy"),
                             optax.sgd(0.01), create_mesh(2))
    tr = AsyncDOWNPOUR(spec, num_workers=2)
    with pytest.raises(ValueError, match="no PRNG plumbing"):
        tr.train(_data(n=64))
