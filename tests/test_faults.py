"""Fault-tolerance tests (issue 4): deterministic chaos harness, PSClient
reconnect/backoff, hub snapshots + clock fence, idle eviction + heartbeat,
elastic membership, worker supervision, and the end-to-end
kill-hub-and-recover acceptance run.

Every injected fault is SCHEDULED (runtime/faults.py), so a failure here
replays bit-identically from its seed/plan."""

import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from distkeras_tpu.runtime import networking as net
from distkeras_tpu.runtime.faults import (
    ChaosProxy,
    Fault,
    FaultPlan,
    InjectedWorkerFault,
    WorkerKillPlan,
)
from distkeras_tpu.runtime.parameter_server import (
    ADAGParameterServer,
    DeltaParameterServer,
    DynSGDParameterServer,
    PSClient,
)


def _weights():
    return [np.zeros((2, 2), np.float32), np.zeros((3,), np.float32)]


def _ones():
    return [np.ones((2, 2), np.float32), np.ones((3,), np.float32)]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_until(pred, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# -- fault plans ---------------------------------------------------------------

def test_fault_plan_seeded_determinism_and_lookup():
    a = FaultPlan.random(seed=7, conns=4, frames=10, n_faults=3,
                         kinds=("sever", "delay", "truncate"))
    b = FaultPlan.random(seed=7, conns=4, frames=10, n_faults=3,
                         kinds=("sever", "delay", "truncate"))
    assert a.faults == b.faults  # same seed -> identical schedule
    c = FaultPlan.random(seed=8, conns=4, frames=10, n_faults=3,
                         kinds=("sever", "delay", "truncate"))
    assert a.faults != c.faults
    f = a.faults[0]
    assert a.lookup(f.conn, f.direction, f.frame) is f
    assert a.lookup(f.conn, f.direction, f.frame + 10**6) is None
    with pytest.raises(ValueError, match="kind"):
        Fault(conn=0, frame=1, kind="meteor")


def test_worker_kill_plan_fires_once_per_pair():
    plan = WorkerKillPlan([(1, 2)], seed=0)
    plan.hook(0, 2)  # other worker: no-op
    plan.hook(1, 1)
    with pytest.raises(InjectedWorkerFault, match="worker 1 dies at window 2"):
        plan.hook(1, 2)
    plan.hook(1, 2)  # replay after restart: fires at most once
    assert plan.fired == [(1, 2)]


# -- chaos proxy ---------------------------------------------------------------

def test_chaos_proxy_passthrough_is_transparent():
    """An empty plan must forward frames byte-exactly: the full PS exchange
    works through the proxy with an unchanged trajectory."""
    ps = DeltaParameterServer(_weights())
    ps.start()
    try:
        with ChaosProxy("127.0.0.1", ps.port) as proxy:
            with PSClient("127.0.0.1", proxy.port, templates=_weights()) as c:
                assert all(np.all(w == 0) for w in c.pull())
                c.commit(_ones())
                w = c.pull()
                np.testing.assert_allclose(w[0], np.ones((2, 2)))
        assert ps.num_updates == 1
        assert proxy.faults_fired == []
    finally:
        ps.stop()


def test_chaos_sever_client_reconnects_and_recovers():
    """A severed weights reply mid-pipeline: the client reconnects (through
    the proxy, as a fresh conn ordinal the plan leaves alone), re-pulls,
    and every subsequent exchange lands — the hub's center never skips."""
    ps = DeltaParameterServer(_weights())
    ps.start()
    plan = FaultPlan([Fault(conn=0, direction="s2c", frame=2, kind="sever")])
    try:
        with ChaosProxy("127.0.0.1", ps.port, plan) as proxy:
            with PSClient("127.0.0.1", proxy.port, templates=_weights(),
                          max_reconnects=5, reconnect_backoff=0.02) as c:
                for _ in range(4):
                    c.pull()
                    c.commit(_ones())
                w = c.pull()
            assert len(proxy.faults_fired) == 1
        assert c.reconnects_used >= 1
        # commits may be dropped across the fault (never half-applied, never
        # doubled): the center is an exact integer multiple of the delta
        applied = float(w[0][0, 0])
        assert applied == ps.num_updates
        assert 1 <= ps.num_updates <= 4
    finally:
        ps.stop()


def test_chaos_truncate_desyncs_then_recovers():
    """A frame truncated mid-payload (crashed peer shape) must not hang
    either end: the hub drops the connection, the client reconnects and
    finishes its exchanges."""
    ps = DeltaParameterServer(_weights())
    ps.start()
    plan = FaultPlan([Fault(conn=0, direction="c2s", frame=3,
                            kind="truncate", keep_bytes=6)])
    try:
        with ChaosProxy("127.0.0.1", ps.port, plan) as proxy:
            with PSClient("127.0.0.1", proxy.port, templates=_weights(),
                          max_reconnects=5, reconnect_backoff=0.02,
                          timeout=10.0) as c:
                for _ in range(4):
                    c.pull()
                    c.commit(_ones())
            assert len(proxy.faults_fired) == 1
        assert c.reconnects_used >= 1
        assert ps.num_updates >= 1
    finally:
        ps.stop()


# -- reconnect/backoff bounds --------------------------------------------------

def test_reconnect_storm_bounded_by_budget_and_backoff():
    """A hub that never comes back: attempts stop at max_reconnects, total
    backoff stays within the exponential schedule's [0.5x, 1x] jitter
    envelope, and the surfaced error is a clean ConnectionError."""
    ps = DeltaParameterServer(_weights())
    ps.start()
    c = PSClient("127.0.0.1", ps.port, templates=_weights(),
                 max_reconnects=3, reconnect_backoff=0.05,
                 reconnect_backoff_max=0.2)
    c.pull()  # known-good connection
    ps.stop()
    t0 = time.monotonic()
    with pytest.raises(ConnectionError, match="reconnect budget"):
        for _ in range(100):
            c.pull()
    elapsed = time.monotonic() - t0
    assert c.reconnects_used == 3
    # schedule: 0.05, 0.1, 0.2 -> jittered total in [0.175, 0.35] plus
    # small connect-refused overheads; the bound that matters is "no
    # unbounded storm, no premature give-up"
    assert 0.17 <= elapsed < 5.0
    c.sock.close()


def test_default_client_faults_exactly_as_before():
    """max_reconnects=0 (the default) must preserve the pre-resilience
    contract: the first fault surfaces immediately, no retries."""
    ps = DeltaParameterServer(_weights())
    ps.start()
    c = PSClient("127.0.0.1", ps.port, templates=_weights())
    c.pull()
    ps.stop()
    with pytest.raises((ConnectionError, OSError, ValueError)):
        for _ in range(100):
            c.pull()
    assert c.reconnects_used == 0
    c.sock.close()


# -- idle eviction + heartbeat -------------------------------------------------

def test_hub_evicts_half_open_connection():
    """Satellite: a peer that goes silent (half-open) must not park its
    handler forever — the idle timeout evicts it and frees the slot."""
    ps = DeltaParameterServer(_weights(), idle_timeout=0.3)
    ps.start()
    try:
        c = PSClient("127.0.0.1", ps.port, templates=_weights())
        c.pull()
        c.commit(_ones())  # join membership: a real worker going silent
        assert _wait_until(lambda: ps.live_workers() == 1)
        # silence > idle_timeout: handler times out, membership drops
        assert _wait_until(lambda: ps.live_workers() == 0, timeout=5.0), \
            "idle worker was not evicted"
        assert _wait_until(lambda: not any(t.is_alive() for t in ps._handlers))
        c.sock.close()
        # the hub still serves fresh connections after the eviction
        with PSClient("127.0.0.1", ps.port, templates=_weights()) as c2:
            np.testing.assert_allclose(c2.pull()[0], np.ones((2, 2)))
    finally:
        ps.stop()


def test_heartbeat_keeps_idle_worker_alive():
    """A slow-but-alive worker (long window, no traffic) heartbeats through
    the idle window: no eviction, membership retained, next exchange
    proceeds on the SAME connection (no reconnect consumed)."""
    ps = DeltaParameterServer(_weights(), idle_timeout=0.6)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      max_reconnects=2, heartbeat_interval=0.15) as c:
            c.pull()
            c.commit(_ones())
            time.sleep(1.5)  # >> idle_timeout: only heartbeats cross
            assert ps.live_workers() == 1
            c.commit(_ones())
            np.testing.assert_allclose(c.pull()[0], np.full((2, 2), 2.0))
            assert c.reconnects_used == 0
    finally:
        ps.stop()


# -- elastic membership --------------------------------------------------------

def test_adag_elastic_live_count_scaling():
    """The acceptance assertion on ADAG's denominator: with elastic=True the
    scale follows LIVE membership — 1/1 while one worker has committed,
    1/2 with two, back to 1/1 after a worker leaves — clamped so it never
    exceeds the configured cohort."""
    ps = ADAGParameterServer(_weights(), num_workers=4, elastic=True,
                             idle_timeout=30.0)
    ps.start()
    try:
        a = PSClient("127.0.0.1", ps.port, templates=_weights())
        b = PSClient("127.0.0.1", ps.port, templates=_weights())
        a.pull()
        b.pull()
        a.commit(_ones())           # members: {a} -> scaled 1/1
        assert _wait_until(lambda: ps.live_workers() == 1)
        np.testing.assert_allclose(ps.get_weights()[0], np.ones((2, 2)))
        b.commit(_ones())           # members: {a, b} -> scaled 1/2
        np.testing.assert_allclose(ps.get_weights()[0], np.full((2, 2), 1.5))
        b.close()                   # b departs: denominator falls back to 1
        assert _wait_until(lambda: ps.live_workers() == 1), \
            "membership did not drop after disconnect"
        a.commit(_ones())
        np.testing.assert_allclose(ps.get_weights()[0], np.full((2, 2), 2.5))
        a.close()
    finally:
        ps.stop()


def test_adag_elastic_inproc_commits_use_static_denominator():
    """commit_direct bypasses connection membership (inproc transport), so
    elastic hubs must fall back to the STATIC denominator there — never
    to 1/1, which would over-apply every inproc delta num_workers-fold."""
    ps = ADAGParameterServer(_weights(), num_workers=4, elastic=True)
    ps.start()
    try:
        assert ps.live_workers() == 0
        ps.commit_direct([np.full((2, 2), 4.0, np.float32),
                          np.full((3,), 4.0, np.float32)], 0)
        np.testing.assert_allclose(ps.get_weights()[0], np.ones((2, 2)))
    finally:
        ps.stop()


def test_adag_static_denominator_unchanged_by_default():
    ps = ADAGParameterServer(_weights(), num_workers=4)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights()) as c:
            c.commit([np.full((2, 2), 4.0, np.float32),
                      np.full((3,), 4.0, np.float32)])
            np.testing.assert_allclose(c.pull()[0], np.ones((2, 2)))
    finally:
        ps.stop()


# -- hub snapshots + clock fence -----------------------------------------------

@pytest.mark.parametrize("hub_kind", ["python", "native"])
def test_hub_kill_and_restore_from_snapshot(tmp_path, hub_kind):
    """Kill a hub (no final snapshot — crash semantics) and restart a
    replacement from the last periodic snapshot on the SAME port: center,
    clock and update count resume; a reconnecting client continues
    committing against the restored center."""
    if hub_kind == "native":
        from distkeras_tpu.runtime.native import native_available
        if not native_available():
            pytest.skip("no C++ toolchain for the native hub")

    snap_dir = str(tmp_path / f"hub-snap-{hub_kind}")
    port = _free_port()

    def make_hub(restore):
        if hub_kind == "native":
            from distkeras_tpu.runtime.native import MODE_DELTA, NativeParameterServer
            return NativeParameterServer(_weights(), mode=MODE_DELTA, port=port,
                                         snapshot_dir=snap_dir,
                                         snapshot_interval=60.0, restore=restore)
        return DeltaParameterServer(_weights(), port=port, snapshot_dir=snap_dir,
                                    snapshot_interval=60.0, restore=restore)

    ps1 = make_hub(restore=False)
    ps1.start()
    with PSClient("127.0.0.1", port, templates=_weights(),
                  max_reconnects=20, reconnect_backoff=0.05) as c:
        c.pull()
        c.commit(_ones())
        c.commit(_ones())
        ps1.snapshotter.save_now()   # the "periodic" snapshot the crash eats up to
        c.commit(_ones())            # post-snapshot commit: lost by the crash
        ps1.kill()
        ps2 = make_hub(restore=True)
        ps2.start()                  # same port, restored center
        try:
            w = c.pull()             # client reconnects via backoff
            np.testing.assert_allclose(w[0], np.full((2, 2), 2.0))
            assert c.reconnects_used >= 1
            assert ps2.num_updates == 2  # update count resumed from snapshot
            c.commit(_ones())        # training continues against the restoree
            np.testing.assert_allclose(c.pull()[0], np.full((2, 2), 3.0))
        finally:
            ps2.stop()


def test_clock_fence_rejects_pre_restart_stale_clocks(tmp_path):
    """DynSGD makes the fence observable: a client presenting a
    pre-restart pull clock (0) to a hub restored at clock 50 must be
    scaled as if it pulled AT the restart (staleness 0 -> full delta), not
    as 50 commits stale (-> delta/51)."""
    ps1 = DynSGDParameterServer(_weights(), snapshot_dir=str(tmp_path / "s"),
                                snapshot_interval=60.0)
    ps1.start()
    for _ in range(50):
        ps1.commit_direct(_ones(), last_pull_clock=ps1._clock)
    ps1.snapshotter.save_now()
    ps1.kill()

    ps2 = DynSGDParameterServer(_weights(), snapshot_dir=str(tmp_path / "s"),
                                snapshot_interval=60.0, restore=True)
    ps2.start()
    try:
        assert ps2._clock == 50 and ps2.num_updates == 50
        before = ps2.get_weights()[0].copy()
        ps2.commit_direct(_ones(), last_pull_clock=0)  # pre-restart clock
        after = ps2.get_weights()[0]
        # fenced to staleness 0: the FULL delta landed (not 1/51 of it)
        np.testing.assert_allclose(after - before, np.ones((2, 2)), rtol=1e-6)
    finally:
        ps2.stop()


def test_hub_snapshot_skips_corrupt_latest(tmp_path):
    """A torn latest snapshot (disk truncation) is skipped with a warning;
    the hub restores from the previous good one."""
    snap_dir = str(tmp_path / "snaps")
    ps1 = DeltaParameterServer(_weights(), snapshot_dir=snap_dir,
                               snapshot_interval=60.0)
    ps1.start()
    ps1.commit_direct(_ones(), 0)
    ps1.snapshotter.save_now()       # good snapshot: center == 1
    ps1.commit_direct(_ones(), 0)
    ps1.snapshotter.save_now()       # snapshot to corrupt: center == 2
    ps1.kill()
    latest = sorted(os.listdir(snap_dir))[-1]
    npz = [f for f in os.listdir(os.path.join(snap_dir, latest))
           if f.endswith(".npz")][0]
    with open(os.path.join(snap_dir, latest, npz), "wb") as f:
        f.write(b"not a zipfile")

    ps2 = DeltaParameterServer(_weights(), snapshot_dir=snap_dir,
                               snapshot_interval=60.0, restore=True)
    with pytest.warns(UserWarning, match="skipping unreadable PS snapshot"):
        ps2.start()
    try:
        np.testing.assert_allclose(ps2.get_weights()[0], np.ones((2, 2)))
    finally:
        ps2.stop()


def test_restore_racing_save_loop_never_loses_a_step(tmp_path):
    """Guarded-by regression (ISSUE 14): ``restore_latest`` advances
    ``_next_step`` under the save lock, so a restore racing the periodic
    save loop cannot lose-update the step counter — every concurrent
    save_now lands on a distinct step directory."""
    import threading

    snap_dir = str(tmp_path / "snaps")
    ps = DeltaParameterServer(_weights(), snapshot_dir=snap_dir,
                              snapshot_interval=60.0)
    ps.start()
    ps.commit_direct(_ones(), 0)
    ps.snapshotter.save_now()
    stop = threading.Event()
    errors = []

    def saver():
        try:
            while not stop.is_set():
                ps.snapshotter.save_now()
        except Exception as e:  # pragma: no cover - the regression
            errors.append(e)

    t = threading.Thread(target=saver)
    t.start()
    try:
        for _ in range(20):
            assert ps.snapshotter.restore_latest()
    finally:
        stop.set()
        t.join()
        ps.kill()
    assert not errors, errors
    steps = sorted(int(d.split("_")[-1]) for d in os.listdir(snap_dir))
    assert steps and ps.snapshotter._next_step > max(steps)


def test_restore_refuses_when_snapshots_exist_but_none_readable(tmp_path):
    """Progress on disk that cannot be read must stop the hub, not let it
    silently serve fresh weights; an EMPTY dir (first boot under a
    restart-with-restore supervisor) only warns."""
    snap_dir = str(tmp_path / "snaps")
    ps1 = DeltaParameterServer(_weights(), snapshot_dir=snap_dir,
                               snapshot_interval=60.0)
    ps1.start()
    ps1.commit_direct(_ones(), 0)
    ps1.snapshotter.save_now()
    ps1.kill()
    for step in os.listdir(snap_dir):
        npz = [f for f in os.listdir(os.path.join(snap_dir, step))
               if f.endswith(".npz")][0]
        with open(os.path.join(snap_dir, step, npz), "wb") as f:
            f.write(b"torn")
    ps2 = DeltaParameterServer(_weights(), snapshot_dir=snap_dir,
                               snapshot_interval=60.0, restore=True)
    with pytest.warns(UserWarning):
        with pytest.raises(RuntimeError, match="none is readable"):
            ps2.start()
    # restore without any snapshot dir at all is a constructor error
    with pytest.raises(ValueError, match="requires snapshot_dir"):
        DeltaParameterServer(_weights(), restore=True)
    # first boot: empty dir warns and serves initial weights
    ps3 = DeltaParameterServer(_weights(), snapshot_dir=str(tmp_path / "new"),
                               snapshot_interval=60.0, restore=True)
    with pytest.warns(UserWarning, match="no snapshot exists yet"):
        ps3.start()
    ps3.stop()


# -- trainer-level supervision matrix ------------------------------------------

def _tiny_dataset(n=256, seed=0):
    from distkeras_tpu.data.dataset import Dataset

    rng = np.random.default_rng(seed)
    half = n // 2
    x = np.concatenate([
        rng.normal(loc=-2.0, scale=1.0, size=(half, 8)),
        rng.normal(loc=+2.0, scale=1.0, size=(half, 8))]).astype(np.float32)
    y = np.concatenate([np.zeros(half, np.int64), np.ones(half, np.int64)])
    perm = rng.permutation(n)
    return Dataset({"features": x[perm],
                    "label": np.eye(2, dtype=np.float32)[y[perm]]})


def _mlp_spec():
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))


_ALL_TRAINERS = ["AsyncDOWNPOUR", "AsyncADAG", "AsyncDynSGD", "AsyncAEASGD",
                 "AsyncEAMSGD"]


def _make_trainer(trainer_name, hub, transport, **extra):
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model

    cls = getattr(dk, trainer_name)
    kwargs = dict(loss="categorical_crossentropy", batch_size=16, num_epoch=1,
                  num_workers=2, communication_window=2, learning_rate=0.05,
                  seed=0, native_ps=(hub == "native"), transport=transport)
    if trainer_name in ("AsyncAEASGD", "AsyncEAMSGD"):
        kwargs["rho"] = 2.0
    kwargs.update(extra)
    return cls(Model.init(_mlp_spec(), seed=0), **kwargs)


@pytest.mark.parametrize("trainer_name", _ALL_TRAINERS)
@pytest.mark.parametrize("hub", ["python", "native"])
@pytest.mark.parametrize("transport", ["socket", "inproc"])
def test_worker_killed_mid_window_is_restarted(trainer_name, hub, transport):
    """The satellite fault-injection matrix: all five Async* trainers x
    {socket, inproc} x {python, native} hubs — a worker killed mid-window
    by a seeded plan is restarted by the supervisor from the hub's current
    center, the run completes with no recorded error, and the hub applied
    commits from both workers."""
    if hub == "native":
        from distkeras_tpu.runtime.native import native_available
        if not native_available():
            pytest.skip("no C++ toolchain for the native hub")

    plan = WorkerKillPlan([(1, 1)], seed=4)
    trainer = _make_trainer(trainer_name, hub, transport,
                            on_worker_failure="restart", max_worker_restarts=2,
                            fault_hook=plan.hook,
                            max_reconnects=3, reconnect_backoff=0.02)
    trainer.train(_tiny_dataset())
    assert plan.fired == [(1, 1)]
    assert trainer.worker_restarts == 1
    assert trainer.worker_errors == []
    assert trainer.parameter_server.num_updates > 4  # both workers committed
    assert len(trainer.history) > 0


def test_restart_budget_exhaustion_degrades_to_continue():
    """A worker that dies on EVERY attempt exhausts max_worker_restarts;
    the error is recorded, survivors finish, and the run returns a model
    (restart degrades to continue, never to a hang)."""
    def always_kill_worker_1(idx, window):
        if idx == 1:
            raise InjectedWorkerFault("worker 1 always dies")

    trainer = _make_trainer("AsyncADAG", "python", "socket",
                            on_worker_failure="restart", max_worker_restarts=2,
                            fault_hook=always_kill_worker_1)
    model = trainer.train(_tiny_dataset())
    assert trainer.worker_restarts == 2          # budget fully used
    assert len(trainer.worker_errors) == 1       # then recorded, not raised
    assert isinstance(trainer.worker_errors[0], InjectedWorkerFault)
    assert model.predict(_tiny_dataset()["features"][:4]).shape == (4, 2)


def test_elastic_trainer_survives_permanent_worker_death(toy_dataset):
    """Degraded-but-correct: elastic ADAG + a permanently dead worker —
    the survivors' commits stop being diluted by the ghost's 1/num_workers
    share and the run still learns the toy task."""
    from distkeras_tpu.data.transformers import LabelIndexTransformer
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.predictors import ModelPredictor

    plan = WorkerKillPlan([(1, 1)], seed=0)
    trainer = _make_trainer("AsyncADAG", "python", "socket",
                            num_epoch=2, elastic=True,
                            on_worker_failure="continue", fault_hook=plan.hook)
    model = trainer.train(toy_dataset)
    assert len(trainer.worker_errors) == 1
    assert trainer.parameter_server.elastic
    ds = ModelPredictor(model, features_col="features").predict(toy_dataset)
    ds = LabelIndexTransformer().transform(ds)
    acc = AccuracyEvaluator(prediction_col="prediction_index",
                            label_col="label_index").evaluate(ds)
    assert acc > 0.9, f"elastic degraded run underperformed: {acc}"


# -- end-to-end kill-and-recover (the issue-4 acceptance run) ------------------

def test_hub_kill_restart_recovery_end_to_end(toy_dataset, tmp_path):
    """The acceptance criterion, end to end: the hub dies abruptly mid-run
    (crash semantics — no final snapshot), a replacement restores the last
    periodic snapshot on the same port, workers reconnect via backoff and
    finish training; the final trajectory lands within tolerance of the
    fault-free run and the recovered model still solves the task."""
    from distkeras_tpu.data.transformers import LabelIndexTransformer
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.predictors import ModelPredictor
    from distkeras_tpu.runtime.launcher import start_parameter_server

    common = dict(loss="categorical_crossentropy", batch_size=16, num_epoch=3,
                  num_workers=2, communication_window=2, learning_rate=0.05,
                  seed=0)

    # fault-free reference trajectory
    import distkeras_tpu as dk

    ref = dk.AsyncADAG(Model.init(_mlp_spec(), seed=0), **common)
    ref.train(toy_dataset)
    ref_loss = float(np.mean(ref.history[-8:]))

    # chaos run: external hub with periodic snapshots, killed mid-run
    snap_dir = str(tmp_path / "hub-snaps")
    port = _free_port()
    model0 = Model.init(_mlp_spec(), seed=0)
    hub_kwargs = dict(mode="adag", num_workers=2, port=port,
                      snapshot_dir=snap_dir, snapshot_interval=0.1,
                      idle_timeout=30.0)
    ps1 = start_parameter_server(model0, **hub_kwargs)
    state = {"ps2": None, "killed_at": None}

    def killer():
        # wait until training is genuinely mid-run AND a periodic snapshot
        # exists, then crash the hub and restart it from the snapshot
        _wait_until(lambda: ps1.num_updates >= 8
                    and ps1.snapshotter.checkpointer.latest_step() is not None,
                    timeout=120.0)
        state["killed_at"] = ps1.num_updates
        ps1.kill()
        ps2 = start_parameter_server(model0, restore=True, **hub_kwargs)
        state["ps2"] = ps2

    kthread = threading.Thread(target=killer)
    kthread.start()
    trainer = dk.AsyncADAG(Model.init(_mlp_spec(), seed=0),
                           ps_address=("127.0.0.1", port),
                           max_reconnects=40, reconnect_backoff=0.05,
                           **common)
    try:
        model = trainer.train(toy_dataset)
    finally:
        kthread.join(timeout=120)
    ps2 = state["ps2"]
    assert ps2 is not None, "hub was never killed/restarted (run too fast?)"
    try:
        assert state["killed_at"] >= 8
        assert ps2.num_updates > 0  # post-restart commits landed
        # recovery quality: the final trajectory is within tolerance of the
        # fault-free one, and the model still solves the task
        final_loss = float(np.mean(trainer.history[-8:]))
        assert abs(final_loss - ref_loss) < 0.5, \
            f"post-recovery loss {final_loss} vs fault-free {ref_loss}"
        ds = ModelPredictor(model, features_col="features").predict(toy_dataset)
        ds = LabelIndexTransformer().transform(ds)
        acc = AccuracyEvaluator(prediction_col="prediction_index",
                                label_col="label_index").evaluate(ds)
        assert acc > 0.85, f"recovered model accuracy {acc}"
    finally:
        ps2.stop()


@pytest.mark.slow
def test_hub_sigkill_subprocess_soak(toy_dataset, tmp_path):
    """Soak: a REAL `distkeras-ps` process SIGKILLed mid-run and relaunched
    with --restore — the full deployment shape (process death, not an
    in-process stand-in).  Slow-marked: subprocess startup pays full
    import+jax init twice."""
    from distkeras_tpu.models.base import Model

    import distkeras_tpu as dk

    model0 = Model.init(_mlp_spec(), seed=0)
    model_path = str(tmp_path / "model.bin")
    with open(model_path, "wb") as f:
        f.write(model0.serialize())
    snap_dir = str(tmp_path / "snaps")
    port = _free_port()

    def launch(restore):
        args = [sys.executable, "-m", "distkeras_tpu.runtime.launcher",
                "--model", model_path, "--mode", "adag", "--num-workers", "2",
                "--port", str(port), "--snapshot-dir", snap_dir,
                "--snapshot-interval", "0.2"]
        if restore:
            args.append("--restore")
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.Popen(
            args, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=repo_root,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root))
        for _ in range(200):  # warnings may precede the banner
            line = proc.stdout.readline()
            if not line or "listening" in line:
                break
        assert "listening" in line, f"hub never came up: {line!r}"
        return proc

    proc1 = launch(restore=False)
    result = {}

    def run_trainer():
        trainer = dk.AsyncADAG(
            Model.init(_mlp_spec(), seed=0), loss="categorical_crossentropy",
            batch_size=16, num_epoch=3, num_workers=2, communication_window=2,
            learning_rate=0.05, seed=0, ps_address=("127.0.0.1", port),
            max_reconnects=60, reconnect_backoff=0.1)
        trainer.train(toy_dataset)
        result["history"] = trainer.history

    t = threading.Thread(target=run_trainer)
    t.start()
    # let training make progress past at least one snapshot, then SIGKILL
    assert _wait_until(
        lambda: os.path.isdir(snap_dir) and
        any(n.startswith("step_") for n in os.listdir(snap_dir)),
        timeout=120.0)
    time.sleep(0.5)
    proc1.send_signal(signal.SIGKILL)
    proc1.wait(timeout=30)
    proc2 = launch(restore=True)
    try:
        t.join(timeout=300)
        assert not t.is_alive(), "trainer did not finish after hub restart"
        assert len(result.get("history", [])) > 0
    finally:
        proc2.terminate()
        proc2.wait(timeout=30)


# -- frame-header sanity (satellite) -------------------------------------------

def test_garbage_length_prefix_is_typed_and_bounded():
    """A garbage 8-byte prefix declaring an absurd frame must raise
    ProtocolError BEFORE allocating, and a hub receiving one must drop the
    connection and keep serving."""
    import struct

    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack(">Q", 1 << 40))
        buf = bytearray(64)
        with pytest.raises(net.ProtocolError, match="exceeds limit"):
            net.recv_frame_into(b, buf, limit=1024)
        assert len(buf) == 64  # nothing was grown toward the declared size
    finally:
        a.close()
        b.close()

    assert issubclass(net.ProtocolError, ValueError)  # except ValueError holds

    ps = DeltaParameterServer(_weights())
    ps.start()
    try:
        raw = socket.create_connection(("127.0.0.1", ps.port))
        raw.sendall(struct.pack(">Q", 1 << 40) + b"junk")
        # hub rejects and closes promptly (no hang): EOF, or RST when our
        # unread junk was still in the hub's receive buffer at close
        raw.settimeout(5.0)
        try:
            assert raw.recv(1) == b""
        except ConnectionResetError:
            pass
        raw.close()
        # and the hub still serves a well-behaved client afterwards
        with PSClient("127.0.0.1", ps.port, templates=_weights()) as c:
            c.commit(_ones())
            np.testing.assert_allclose(c.pull()[0], np.ones((2, 2)))
    finally:
        ps.stop()
