"""Pallas flash attention vs the dense reference implementation.

Runs the real kernels through the Pallas interpreter on CPU (same code
path as TPU modulo Mosaic lowering)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.attention import dense_attention
from distkeras_tpu.ops.flash_attention import flash_attention


def _rand_qkv(rng, b=2, l=64, h=2, d=32, lk=None, dtype=np.float32):
    lk = l if lk is None else lk
    q = rng.normal(size=(b, l, h, d)).astype(dtype)
    k = rng.normal(size=(b, lk, h, d)).astype(dtype)
    v = rng.normal(size=(b, lk, h, d)).astype(dtype)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_dense(causal):
    q, k, v = _rand_qkv(np.random.default_rng(0))
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32, interpret=True)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_forward_with_offsets():
    # flash over the second half of the queries against the full key set ==
    # the corresponding slice of full dense attention (a ring-attention shard)
    rng = np.random.default_rng(1)
    q, k, v = _rand_qkv(rng, l=64)
    q_half = q[:, 32:]
    out = flash_attention(q_half, k, v, causal=True, q_offset=32, k_offset=0,
                          block_q=16, block_k=16, interpret=True)
    ref = dense_attention(q, k, v, causal=True)[:, 32:]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_grads_match_dense(causal):
    rng = np.random.default_rng(2)
    q, k, v = _rand_qkv(rng, b=1, l=32, h=2, d=16)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16, interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_dense(q, k, v):
        o = dense_attention(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5,
                                   err_msg=f"grad mismatch for {name}")


def test_bfloat16_forward():
    q, k, v = _rand_qkv(np.random.default_rng(3), d=32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb, causal=True, block_q=32, block_k=32, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)


def test_fully_masked_rows_zero_output_and_grads():
    # q_offset < k_offset: the first 8 query rows precede every key — they
    # must output exactly 0 with finite (zero) gradients, in both impls
    rng = np.random.default_rng(5)
    q, k, v = _rand_qkv(rng, b=1, l=16, h=1, d=16, lk=16)

    out = flash_attention(q, k, v, causal=True, q_offset=0, k_offset=8,
                          block_q=16, block_k=16, interpret=True)
    ref = dense_attention(q, k, v, causal=True, q_offset=0, k_offset=8)
    np.testing.assert_array_equal(np.asarray(out[:, :8]), 0.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def loss(fn):
        def f(q, k, v):
            if fn is flash_attention:
                o = fn(q, k, v, causal=True, q_offset=0, k_offset=8,
                       block_q=16, block_k=16, interpret=True)
            else:
                o = fn(q, k, v, causal=True, q_offset=0, k_offset=8)
            return jnp.sum(o * o)
        return f

    gf = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss(dense_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        assert np.isfinite(np.asarray(a)).all(), f"non-finite flash grad for {name}"
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5,
                                   err_msg=f"grad mismatch for {name}")


def test_unknown_impl_raises():
    from distkeras_tpu.ops.attention import attention

    q, k, v = _rand_qkv(np.random.default_rng(6), l=16, d=8)
    with pytest.raises(ValueError, match="unknown attention impl"):
        attention(q, k, v, impl="Flash")


def test_mosaic_illegal_length_raises():
    # L=513 with a sub-length requested block has no 8-divisible divisor
    # (513 is odd, so _pick_block halves down to 1); flash must reject it
    # with a clear error instead of failing in Mosaic lowering.  A block
    # request >= L falls back to the full length (513 == L, legal), so pin
    # both blocks below L to hit the validation path deterministically.
    q, k, v = _rand_qkv(np.random.default_rng(7), l=513, d=8)
    with pytest.raises(ValueError, match="Mosaic-legal"):
        flash_attention(q, k, v, block_q=256, block_k=256, interpret=True)


def test_forced_impl_under_sequence_parallelism_selects_ring_block():
    """Under a bound sequence axis the schedule stays ring attention and
    ``impl`` selects the PER-BLOCK compute — both choices must match the
    dense full-sequence reference."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from distkeras_tpu.ops.attention import attention

    devs = np.array(jax.devices()[:2])
    mesh = Mesh(devs, ("sp",))
    q, k, v = _rand_qkv(np.random.default_rng(8), l=16, d=8)
    ref = dense_attention(q, k, v, causal=True)

    for impl in ("dense", "flash"):
        fn = jax.shard_map(
            lambda q, k, v, i=impl: attention(q, k, v, axis_name="sp", impl=i),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"))
        np.testing.assert_allclose(np.asarray(fn(q, k, v)), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5,
                                   err_msg=f"ring per-block impl={impl}")


def test_odd_block_sizes_fall_back_to_divisors():
    # L=48 with requested block 32 -> picker must choose a divisor
    q, k, v = _rand_qkv(np.random.default_rng(4), l=48)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_split_backward_fallback_matches_fused():
    """The two-kernel backward (taken when the fused kernel's [Lq, D] dq
    scratch would overflow scoped vmem) must produce the same gradients as
    the fused default."""
    import distkeras_tpu.ops.flash_attention as fa

    rng = np.random.default_rng(9)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 64, 2, 16)) * 0.1, jnp.float32)
               for _ in range(3))

    def grads():
        return jax.grad(
            lambda q, k, v: jnp.sum(fa.flash_attention(
                q, k, v, causal=True, block_q=16, block_k=16, interpret=True)),
            argnums=(0, 1, 2))(q, k, v)

    assert fa._fused_bwd_ok(64, 16, 16, 16, 64)
    fused = grads()
    caps = fa._FUSED_WIDE_CAP, fa._FUSED_DQ_SCRATCH_CAP
    try:
        fa._FUSED_WIDE_CAP = fa._FUSED_DQ_SCRATCH_CAP = 0
        assert not fa._fused_bwd_ok(64, 16, 16, 16, 64)
        split = grads()
    finally:
        fa._FUSED_WIDE_CAP, fa._FUSED_DQ_SCRATCH_CAP = caps
    for a, b, name in zip(fused, split, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6,
                                   err_msg=f"fused/split grad mismatch for {name}")


def test_single_block_bwd_tier_selection():
    """The round-5 wide tier: auto-select takes the single-block fused
    backward exactly when the forward runs full-length blocks (Lq = Lk
    <= 2048) past 1024, keeps the (1024, 1024) rung at 8k+, and sizes
    the scoped-vmem grant to the score-tile working set."""
    import distkeras_tpu.ops.flash_attention as fa

    def cfg_for(l):
        q = jnp.zeros((1, l, 4, 128), jnp.bfloat16)
        return fa._make_config(q, q, True, 0, 0, None, None, None, None, True)

    c2k = cfg_for(2048)
    assert (c2k.block_q_bwd, c2k.block_k_bwd) == (2048, 2048)
    c8k = cfg_for(8192)
    assert (c8k.block_q_bwd, c8k.block_k_bwd) == (1024, 1024)
    c1k = cfg_for(1024)  # already single-block under the pre-existing rungs
    assert (c1k.block_q_bwd, c1k.block_k_bwd) == (1024, 1024)
    # the wide tier is gated on the k block spanning the WHOLE sequence:
    # 2048-wide k blocks against a longer sequence are rejected (measured
    # slower at 8k — q-chunks re-stream k/v and give up the causal skip)
    assert not fa._fused_bwd_ok(2048, 128, 2048, 2048, 8192)
    assert fa._fused_bwd_ok(2048, 128, 2048, 2048, 2048)
    # grant sizing: standard 24M through (1024, 1024), 48M for the wide tier
    assert fa._bwd_compiler_params(1024, 1024).vmem_limit_bytes == fa._VMEM_LIMIT
    assert fa._bwd_compiler_params(2048, 2048).vmem_limit_bytes == 48 * 1024 * 1024


def test_bwd_blocks_inherit_explicit_fwd_blocks():
    """Explicit block_q/block_k govern the backward too (multi-block bwd
    scratch accumulation is exercised), and a full-length block on a
    non-8-divisible sequence stays legal for both passes."""
    import jax

    rng = np.random.default_rng(5)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 64, 2, 16)) * 0.1, jnp.float32)
               for _ in range(3))

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v))

    # blocks of 16 over L=64 -> 4x4 bwd grids: cross-block accumulation
    small = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=16, block_k=16, interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(loss(lambda q, k, v: dense_attention(q, k, v, causal=True)),
                   argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(small, ref):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)

    # L=33: only the full-length block is Mosaic-legal; fwd AND bwd must
    # both inherit it rather than erroring on the bwd default of 512->1
    q2, k2, v2 = (jnp.asarray(rng.normal(size=(1, 33, 2, 16)) * 0.1, jnp.float32)
                  for _ in range(3))
    g = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=33, block_k=33, interpret=True)),
        argnums=(0, 1, 2))(q2, k2, v2)
    r = jax.grad(loss(lambda q, k, v: dense_attention(q, k, v, causal=True)),
                 argnums=(0, 1, 2))(q2, k2, v2)
    for got, want in zip(g, r):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)


def test_chunked_fused_backward_matches_single_call():
    """Force the q-chunked fused backward (tiny caps) and check gradients
    against the unchunked default, including the causal q_offset shifts."""
    import distkeras_tpu.ops.flash_attention as fa

    rng = np.random.default_rng(10)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 64, 2, 16)) * 0.1, jnp.float32)
               for _ in range(3))

    def grads():
        return jax.grad(
            lambda q, k, v: jnp.sum(fa.flash_attention(
                q, k, v, causal=True, block_q=16, block_k=16, interpret=True)),
            argnums=(0, 1, 2))(q, k, v)

    whole = grads()
    caps = fa._FUSED_WIDE_CAP, fa._FUSED_DQ_SCRATCH_CAP
    try:
        # cap fits 32 rows of d=16 f32 (2K) -> 64-row input must chunk in 2
        fa._FUSED_WIDE_CAP = fa._FUSED_DQ_SCRATCH_CAP = 32 * 16 * 4
        assert fa._fused_q_chunks(64, 16, 16, 16, 64) == 2
        chunked = grads()
    finally:
        fa._FUSED_WIDE_CAP, fa._FUSED_DQ_SCRATCH_CAP = caps
    for a, b, name in zip(whole, chunked, "qkv"):
        # rtol covers dk/dv cross-chunk summation-order differences
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-8,
                                   err_msg=f"chunked/whole grad mismatch for {name}")


def test_flash_under_dp_shard_map_matches_unsharded():
    """flash_attention must work inside shard_map with vma checking (the
    dp-sharded LM train step) — pallas out_shapes need the inputs' vma.
    Regression: round-3 verify caught ShapeDtypeStruct vma=None errors."""
    from jax.sharding import Mesh, PartitionSpec as P

    rng = np.random.default_rng(11)
    q, k, v = _rand_qkv(rng, b=4, l=32, h=2, d=16)

    def fn(q, k, v):
        def loss(q):
            return jnp.sum(flash_attention(q, k, v, causal=True, block_q=16,
                                           block_k=16, interpret=True))
        return jax.grad(loss)(q)

    devs = np.array(jax.devices()[:2])
    mesh = Mesh(devs, ("dp",))
    sharded = jax.shard_map(fn, mesh=mesh, in_specs=(P("dp"),) * 3,
                            out_specs=P("dp"))
    got = sharded(q, k, v)
    want = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
