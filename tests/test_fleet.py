"""ISSUE-19 tests: the self-scaling fleet — hub-side FleetController,
graceful preemption drain, and multi-job admission control.

Covers the controller's decision rules (spawn cooldown/cap, drift-strike
retirement above the ``min_fleet`` floor, advisory mode, the preemption
respawn authorization), the :class:`SpotPreemptionPlan` drill itself,
job-namespace isolation in both directions, admission control (slot and
byte budgets, re-attach, rejected-session refusal, sparse refusal), the
hub-flavor ``commit_scale`` applied inside a job namespace, the two-job
concurrent isolation drill with the ``fleet_report`` fairness block, the
un-upgraded-client wire-compat matrix (byte-identical across plain /
sharded / replicated hubs that are actively serving other jobs), the
2-of-6 planned-preemption recovery drill (zero acked-commit loss, no
restart budget burned), the ``autoscale=False`` off-path guarantees, and
the ``distkeras-ps`` SIGTERM drain (clean daemon exit + the standby's
replication stream surviving a SIGTERM'd primary untorn).
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from distkeras_tpu import observability as obs
from distkeras_tpu.observability import distributed as dtrace
from distkeras_tpu.observability import health as health_mod
from distkeras_tpu.observability.distributed import fleet_report
from distkeras_tpu.observability.health import HealthCollector, HealthMonitor
from distkeras_tpu.runtime import networking as net
from distkeras_tpu.runtime.faults import SpotPreemptionPlan, WorkerPreempted
from distkeras_tpu.runtime.fleet_controller import FleetController
from distkeras_tpu.runtime.parameter_server import (
    ADAGParameterServer,
    DeltaParameterServer,
    DynSGDParameterServer,
    JobAdmissionError,
    PSClient,
    ShardedParameterServer,
    ShardedPSClient,
    shard_plan,
)

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TESTS_DIR)


@pytest.fixture
def fresh_health():
    """Clean process-default collector/monitor (hubs and autoscale
    trainers bind and subscribe to these at start())."""
    health_mod.reset_default()
    yield health_mod
    health_mod.reset_default()


def _weights():
    return [np.zeros((4, 4), np.float32), np.zeros((6,), np.float32)]


def _monitor(cooldown_s=0.0):
    return HealthMonitor(HealthCollector(), cooldown_s=cooldown_s)


def _wait_until(pred, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return bool(pred())


# -- the controller's decision rules -------------------------------------------

def test_controller_spawns_on_regression_with_cooldown_and_cap():
    mon = _monitor()
    spawned = []
    fc = FleetController(mon, spawn_fn=spawned.append,
                         cooldown_s=3600.0, max_spawns=8)
    try:
        mon.emit("throughput_regression", dedup="a", ratio=0.5)
        mon.emit("throughput_regression", dedup="b", ratio=0.4)
        # the second firing lands inside the spawn cooldown: one spawn
        assert spawned == [None]
        assert fc.stats()["spawns"] == 1
        fc.cooldown_s = 0.0
        for i in range(20):
            mon.emit("throughput_regression", dedup=f"c{i}", ratio=0.3)
        # lifetime cap: a regression spawning cannot fix must not fork-bomb
        assert len(spawned) == 8
        assert fc.stats()["spawns"] == 8
        acts = [d["action"] for d in fc.decisions()]
        assert acts == ["spawn"] * 8
        assert all(d["reason"] == "throughput_regression"
                   for d in fc.decisions())
    finally:
        fc.stop()


def test_controller_retires_after_strikes_never_below_min_fleet():
    mon = _monitor()
    retired = []
    fc = FleetController(mon, retire_fn=retired.append,
                         drift_strikes=2, min_fleet=1)
    try:
        for w in ("0", "1"):
            fc.notify_worker_started(w)
        mon.emit("staleness_drift", worker="0", dedup="s1", z=4.0)
        assert retired == []  # one firing can be a scheduling hiccup
        mon.emit("staleness_drift", worker="0", dedup="s2", z=4.2)
        assert retired == ["0"]
        assert fc.stats()["retires"] == 1
        # worker 1 is the last one above the floor: strikes accrue but
        # the retire is refused
        mon.emit("staleness_drift", worker="1", dedup="s3", z=5.0)
        mon.emit("staleness_drift", worker="1", dedup="s4", z=5.1)
        mon.emit("staleness_drift", worker="1", dedup="s5", z=5.2)
        assert retired == ["0"]
        assert fc.stats()["retires"] == 1
    finally:
        fc.stop()


def test_controller_advisory_mode_records_without_acting():
    """No spawn_fn/retire_fn (the launcher shape): decisions are recorded
    and counted, nothing is called, nothing raises."""
    mon = _monitor()
    fc = FleetController(mon, cooldown_s=0.0, drift_strikes=1)
    try:
        fc.notify_worker_started("0")
        fc.notify_worker_started("1")
        mon.emit("throughput_regression", dedup="r", ratio=0.6)
        mon.emit("staleness_drift", worker="1", dedup="d", z=9.0)
        acts = [(d["action"], d["worker"]) for d in fc.decisions()]
        assert ("spawn", None) in acts
        assert ("retire", "1") in acts
        st = fc.stats()
        assert st["spawns"] == 1 and st["retires"] == 1
        assert st["retiring"] == 1
    finally:
        fc.stop()


def test_controller_preemption_authorizes_respawn_until_stopped():
    mon = _monitor()
    fc = FleetController(mon)
    fc.notify_worker_started("3")
    assert fc.notify_preempted("3", deadline_s=5.0) is True
    fc.notify_drained("3", clean=True)
    assert fc.fleet_size() == 0
    acts = [d["action"] for d in fc.decisions()]
    assert acts == ["respawn", "drained"]
    assert fc.decisions()[0]["evidence"] == {"deadline_s": 5.0}
    assert fc.stats()["preemptions"] == 1
    fc.stop()
    # stopped controller authorizes nothing and the subscription is gone
    assert fc.notify_preempted("4") is False
    mon.emit("throughput_regression", dedup="late", ratio=0.1)
    assert fc.stats()["spawns"] == 0


def test_controller_broken_spawn_fn_never_breaks_the_health_plane():
    mon = _monitor()

    def boom(_):
        raise RuntimeError("spawn backend down")

    fc = FleetController(mon, spawn_fn=boom, cooldown_s=0.0)
    try:
        # the emit path must survive the subscriber's callback failing
        ev = mon.emit("throughput_regression", dedup="x", ratio=0.5)
        assert ev is not None
        assert fc.stats()["spawns"] == 1  # decision recorded regardless
    finally:
        fc.stop()


def test_spot_preemption_plan_fires_once_per_pair():
    plan = SpotPreemptionPlan([(1, 2)], deadline_s=3.0)
    plan.hook(0, 2)  # unplanned worker: no notice
    with pytest.raises(WorkerPreempted) as ei:
        plan.hook(1, 2)
    assert (ei.value.worker, ei.value.window) == (1, 2)
    assert ei.value.deadline_s == 3.0
    plan.hook(1, 2)  # the respawned replacement replays the window freely
    assert plan.fired == [(1, 2)]
    assert len(plan.fired_at) == 1


# -- multi-job admission + namespace isolation ---------------------------------

def test_job_namespace_isolated_both_directions():
    t = _weights()
    ps = DeltaParameterServer(t, port=0, idle_timeout=None)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=t) as c0, \
                PSClient("127.0.0.1", ps.port, templates=t,
                         job="expA") as cj:
            c0.pull()
            cj.pull()
            cj.commit([np.ones_like(x) for x in t])
            # the job's commit never lands on the default center
            got0 = c0.pull()
            assert all(float(np.abs(g).sum()) == 0.0 for g in got0)
            c0.commit([np.full_like(x, 2.0) for x in t])
            # ...and the default commit never lands on the job's center
            gotj = cj.pull()
            for g in gotj:
                np.testing.assert_array_equal(g, np.ones_like(g))
        info = ps.fleet_info()
        assert info["jobs"] == {"expA": {"clock": 1, "num_updates": 1}}
        assert info["jobs_admitted"] == 1 and info["jobs_rejected"] == 0
        assert info["num_updates"] == 1  # the default-namespace commit
    finally:
        ps.stop()


def test_job_center_seeds_from_current_center_and_reattaches():
    t = _weights()
    ps = DeltaParameterServer(t, port=0, idle_timeout=None)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=t) as c0:
            c0.pull()
            c0.commit([np.full_like(x, 3.0) for x in t])
        # a job admitted NOW snapshots the current default center
        with PSClient("127.0.0.1", ps.port, templates=t, job="expB") as cj:
            for g in cj.pull():
                np.testing.assert_array_equal(g, np.full_like(g, 3.0))
            cj.commit([np.ones_like(x) for x in t])
        # re-announcing the same job re-attaches to the existing namespace
        with PSClient("127.0.0.1", ps.port, templates=t, job="expB") as cj2:
            for g in cj2.pull():
                np.testing.assert_array_equal(g, np.full_like(g, 4.0))
        assert ps.fleet_info()["jobs_admitted"] == 1  # one namespace, not two
    finally:
        ps.stop()


def test_admission_default_budget_admits_four_then_slots_exhausted():
    """Defaults: job_budget_bytes = 4x center and max_jobs = 4 admit
    exactly four namespaces; the fifth announce is refused with the slot
    reason and the client surfaces it as JobAdmissionError."""
    t = _weights()
    ps = DeltaParameterServer(t, port=0, idle_timeout=None)
    ps.start()
    try:
        for i in range(4):
            with PSClient("127.0.0.1", ps.port, templates=t,
                          job=f"job{i}") as c:
                c.pull()
        with pytest.raises(JobAdmissionError, match=r"job slots exhausted "
                                                    r"\(4/4\)"):
            PSClient("127.0.0.1", ps.port, templates=t, job="job4",
                     max_reconnects=0)
        info = ps.fleet_info()
        assert sorted(info["jobs"]) == ["job0", "job1", "job2", "job3"]
        assert info["jobs_admitted"] == 4 and info["jobs_rejected"] == 1
    finally:
        ps.stop()


def test_admission_tight_byte_budget_rejects_with_projection():
    t = _weights()
    ps = DeltaParameterServer(t, port=0, idle_timeout=None,
                              job_budget_bytes=1)
    ps.start()
    try:
        with pytest.raises(JobAdmissionError,
                           match="shard memory budget exceeded"):
            PSClient("127.0.0.1", ps.port, templates=t, job="heavy",
                     max_reconnects=0)
        assert ps.fleet_info()["jobs_rejected"] == 1
    finally:
        ps.stop()


def test_admission_disabled_hub_rejects_every_job():
    t = _weights()
    ps = DeltaParameterServer(t, port=0, idle_timeout=None, max_jobs=0)
    ps.start()
    try:
        with pytest.raises(JobAdmissionError,
                           match="multi-job serving is disabled"):
            PSClient("127.0.0.1", ps.port, templates=t, job="any",
                     max_reconnects=0)
    finally:
        ps.stop()


def test_job_session_refuses_sparse_actions():
    """Row-sparse exchange is default-namespace only: a job session that
    sends a sparse pull is severed with a protocol error, never silently
    served from the wrong center."""
    t = [np.zeros((8, 4), np.float32), np.zeros((3,), np.float32)]
    ps = DeltaParameterServer(t, port=0, idle_timeout=None,
                              sparse_leaves=(0,))
    ps.start()
    try:
        c = PSClient("127.0.0.1", ps.port, templates=t, job="sparsejob",
                     sparse_leaves=(0,), max_reconnects=0)
        try:
            with pytest.raises((net.ProtocolError, ConnectionError, OSError)):
                c.pull_nowait(sparse_rows=[np.array([0, 1], np.int64)])
                c.wait_weights()
        finally:
            c.close()
    finally:
        ps.stop()


def test_job_commits_scale_by_hub_flavor_staleness():
    """DynSGD's 1/(s+1) staleness rule applies inside a job namespace
    exactly as on the default center."""
    t = _weights()
    ps = DynSGDParameterServer(t, port=0, idle_timeout=None)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=t, job="dj") as c1, \
                PSClient("127.0.0.1", ps.port, templates=t, job="dj") as c2:
            c1.pull()
            c2.pull()
            c1.commit([np.ones_like(x) for x in t])  # staleness 0: full
            c1.drain()
            c2.commit([np.ones_like(x) for x in t])  # staleness 1: half
            c2.drain()
            with PSClient("127.0.0.1", ps.port, templates=t,
                          job="dj") as c3:
                for g in c3.pull():
                    np.testing.assert_allclose(g, np.full_like(g, 1.5))
    finally:
        ps.stop()


def test_two_job_isolation_drill_and_fairness_report(fresh_health):
    """Two jobs hammer one hub concurrently (plus a default-namespace
    bystander): every namespace lands exactly its own commits, and the
    fleet_report gains the per-job fairness block — which a single-job
    run must NOT grow (report-shape compatibility)."""
    t = _weights()
    obs.reset()
    obs.enable()
    ps = ADAGParameterServer(t, num_workers=4, port=0, idle_timeout=None,
                             elastic=True)
    ps.start()
    commits_per_worker = 6
    errors = []

    def run(job, worker_id, delta_val):
        try:
            ctx = dtrace.TraceContext(job_id=job, worker_id=worker_id,
                                      span_id=dtrace.new_span_id())
            with PSClient("127.0.0.1", ps.port, templates=t, job=job,
                          trace_context=ctx) as c:
                for _ in range(commits_per_worker):
                    c.pull()
                    c.commit([np.full_like(x, delta_val) for x in t])
                c.drain()
        except Exception as e:  # pragma: no cover - surfaced by assert
            errors.append(e)

    try:
        threads = [threading.Thread(target=run, args=("jobA", i, 1.0))
                   for i in range(2)]
        threads += [threading.Thread(target=run, args=("jobB", 2 + i, 2.0))
                    for i in range(2)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        assert not errors, errors
        info = ps.fleet_info()
        assert info["jobs"]["jobA"]["num_updates"] == 2 * commits_per_worker
        assert info["jobs"]["jobB"]["num_updates"] == 2 * commits_per_worker
        assert info["num_updates"] == 0  # the default center never moved
        assert all(float(np.abs(c).sum()) == 0.0 for c in ps.center)

        report = fleet_report(events=obs.TRACER.events())
        jobs = report["jobs"]
        assert sorted(jobs["per_job"]) == ["jobA", "jobB"]
        for j in ("jobA", "jobB"):
            assert jobs["per_job"][j]["commits"] == 2 * commits_per_worker
            assert jobs["per_job"][j]["share"] == 0.5
        assert jobs["max_share"] == jobs["min_share"] == 0.5
        assert set(jobs["ranked"]) == {"jobA", "jobB"}

        # single-job span set: the report keeps its exact prior shape
        single = [e for e in obs.TRACER.events()
                  if e.get("attrs", {}).get("job") == "jobA"]
        assert "jobs" not in fleet_report(events=single)
    finally:
        ps.stop()
        obs.disable()
        obs.reset()


def test_fleet_info_is_json_safe_and_complete():
    import json

    t = _weights()
    ps = DeltaParameterServer(t, port=0, idle_timeout=None)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=t, job="j") as c:
            c.pull()
            c.commit([np.ones_like(x) for x in t])  # membership joins here
            c.drain()
            info = ps.fleet_info()
            assert set(info) == {"live_workers", "jobs", "clock",
                                 "num_updates", "jobs_admitted",
                                 "jobs_rejected"}
            assert info["live_workers"] == 1
            json.dumps(info)  # the launcher/distkeras-top contract
    finally:
        ps.stop()


# -- wire-compat matrix: un-upgraded client vs multi-job hub -------------------

class _RecordingSock:
    def __init__(self, sock):
        self._sock = sock
        self.tx = bytearray()

    def sendall(self, data):
        self.tx += bytes(data)
        return self._sock.sendall(data)

    def __getattr__(self, name):
        return getattr(self._sock, name)


def _assert_no_job_frames(stream: bytes) -> None:
    """A job-unaware client sends no trace/admission announces at all —
    walk the frames and refuse any T (the announce jobs ride on)."""
    i = 0
    while i < len(stream):
        n = int.from_bytes(stream[i:i + 8], "big")
        assert stream[i + 8:i + 9] != net.ACTION_TRACE
        i += 8 + n


def _session_bytes(port, templates):
    with PSClient("127.0.0.1", port, templates=templates) as c:
        rec = _RecordingSock(c.sock)
        c.sock = rec
        c.pull()
        c.commit([np.full_like(t, 0.5) for t in templates])
        c.pull()
        c.drain()
    return bytes(rec.tx)


def test_plain_client_bytes_identical_against_multijob_hub(fresh_health):
    t = _weights()
    plain = DeltaParameterServer(t, port=0, idle_timeout=None)
    busy = DeltaParameterServer(t, port=0, idle_timeout=None)
    plain.start()
    busy.start()
    try:
        # make the second hub genuinely multi-tenant before the probe
        with PSClient("127.0.0.1", busy.port, templates=t,
                      job="tenant") as cj:
            cj.pull()
            cj.commit([np.ones_like(x) for x in t])
            cj.drain()
            baseline = _session_bytes(plain.port, t)
            against_busy = _session_bytes(busy.port, t)
    finally:
        plain.stop()
        busy.stop()
    assert baseline == against_busy
    _assert_no_job_frames(baseline)


def test_plain_striped_client_bytes_identical_on_multijob_shards(
        fresh_health):
    t = [np.zeros((4, 4), np.float32), np.zeros((6,), np.float32),
         np.zeros((3,), np.float32)]
    plan = shard_plan(t, 2)

    def make():
        ps = ShardedParameterServer(
            t, plan, lambda w, sid: DeltaParameterServer(
                w, shard_id=sid, idle_timeout=None))
        ps.start()
        return ps

    def session(ps):
        with ShardedPSClient([("127.0.0.1", p) for p in ps.ports],
                             t, plan) as c:
            recs = []
            for sc in c.shards:
                rec = _RecordingSock(sc.sock)
                sc.sock = rec
                recs.append(rec)
            c.pull()
            c.commit([np.full_like(a, 0.5) for a in t])
            c.pull()
            c.drain()
        return [bytes(r.tx) for r in recs]

    quiet, busy = make(), make()
    try:
        # per-shard tenants: each shard hub of the busy facade is
        # actively serving a job namespace while the probe runs
        tenants = [PSClient("127.0.0.1", port,
                            templates=[t[i] for i in plan.assignments[sid]],
                            job="tenant")
                   for sid, port in enumerate(busy.ports)]
        for tc in tenants:
            tc.pull()
        base_streams = session(quiet)
        busy_streams = session(busy)
        for tc in tenants:
            tc.close()
    finally:
        quiet.stop()
        busy.stop()
    assert base_streams == busy_streams
    for s in base_streams:
        _assert_no_job_frames(s)


def test_plain_client_bytes_identical_against_replicated_multijob_primary(
        fresh_health):
    t = _weights()

    def make():
        primary = DeltaParameterServer(t, port=0, idle_timeout=None)
        primary.start()
        replica = DeltaParameterServer(
            t, idle_timeout=None, replica_of=("127.0.0.1", primary.port))
        replica.start()
        assert replica.wait_synced(timeout=10)
        return primary, replica

    p1, r1 = make()
    p2, r2 = make()
    try:
        with PSClient("127.0.0.1", p2.port, templates=t, job="tenant") as cj:
            cj.pull()
            cj.commit([np.ones_like(x) for x in t])
            cj.drain()
            baseline = _session_bytes(p1.port, t)
            against_busy = _session_bytes(p2.port, t)
        # the default-namespace commit replicated; the job commit did NOT
        # move the replicated (default) center
        assert _wait_until(lambda: r2._clock >= 1)
        np.testing.assert_array_equal(r2.center[0], p2.center[0])
        np.testing.assert_allclose(r2.center[0], np.full_like(t[0], 0.5))
    finally:
        for hub in (r1, p1, r2, p2):
            hub.stop()
    assert baseline == against_busy
    _assert_no_job_frames(baseline)


# -- trainer integration: autoscale, preemption drain, respawn -----------------

def _mlp_spec():
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(name="mlp", config={"hidden_sizes": (16,),
                                         "num_outputs": 2},
                     input_shape=(8,))


def test_autoscale_requires_trainer_owned_hub():
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model

    with pytest.raises(ValueError, match="autoscale"):
        dk.AsyncADAG(Model.init(_mlp_spec(), seed=0), autoscale=True,
                     ps_address=("127.0.0.1", 1))


def test_autoscale_off_constructs_no_controller_and_matches(
        toy_dataset, fresh_health):
    """autoscale=False (the default) builds no FleetController, and
    autoscale=True with zero fleet events trains the bit-identical
    uncontended trajectory — the knob is observationally free until
    something fires (the test_adaptive single-worker parity shape)."""
    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model

    def run(autoscale):
        health_mod.reset_default()
        trainer = dk.AsyncADAG(Model.init(_mlp_spec(), seed=0),
                               loss="categorical_crossentropy",
                               batch_size=16, num_epoch=1, num_workers=1,
                               communication_window=4, learning_rate=0.05,
                               seed=0, autoscale=autoscale)
        model = trainer.train(toy_dataset)
        return trainer, trainer.history, jax.tree.leaves(model.params)

    off, hist_off, params_off = run(False)
    assert off.fleet_controller is None
    assert off.worker_preemptions == []
    on, hist_on, params_on = run(True)
    assert on.fleet_controller is not None
    assert on.fleet_controller.stats()["preemptions"] == 0
    assert hist_off == hist_on
    for a, b in zip(params_off, params_on):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    health_mod.reset_default()


def test_preemption_recovery_drill_two_of_six(toy_dataset, fresh_health):
    """The ISSUE-19 acceptance drill, tier-1 sized: preempt 2 of 6
    workers mid-run; both drain cleanly (every in-flight commit acked,
    zero outstanding), both are respawned WITHOUT burning restart
    budget, and the run finishes with no worker errors."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model

    plan = SpotPreemptionPlan([(4, 1), (5, 1)], deadline_s=5.0)
    trainer = dk.AsyncADAG(
        Model.init(_mlp_spec(), seed=0), loss="categorical_crossentropy",
        batch_size=16, num_epoch=2, num_workers=6, communication_window=2,
        learning_rate=0.05, seed=0, elastic=True, autoscale=True,
        on_worker_failure="restart", max_worker_restarts=1,
        fault_hook=plan.hook)
    trainer.train(toy_dataset)

    assert sorted(plan.fired) == [(4, 1), (5, 1)]
    assert len(trainer.worker_preemptions) == 2
    for p in trainer.worker_preemptions:
        assert p["drained_clean"] is True
        assert p["outstanding_after_drain"] == 0
    st = trainer.fleet_controller.stats()
    assert st["preemptions"] == 2
    # planned capacity loss is not a crash: the full restart budget is
    # intact and nothing errored
    assert trainer.worker_restarts == 0
    assert trainer.worker_errors == []
    acts = [d["action"] for d in trainer.fleet_controller.decisions()]
    assert acts.count("respawn") == 2
    assert acts.count("drained") == 2


# -- distkeras-ps SIGTERM drain ------------------------------------------------

def test_sigterm_primary_never_tears_standby_stream(fresh_health):
    """A SIGTERM'd primary (the launcher path calls ps.stop()) must end
    the replication feed cleanly: the standby holds every replicated
    commit, promotes on the feed loss, and serves the untorn center."""
    t = _weights()
    primary = DeltaParameterServer(t, port=0, idle_timeout=None)
    primary.start()
    replica = DeltaParameterServer(
        t, port=0, idle_timeout=None, replica_feed_retries=0,
        replica_of=("127.0.0.1", primary.port))
    replica.start()
    try:
        assert replica.wait_synced(timeout=10)
        with PSClient("127.0.0.1", primary.port, templates=t) as c:
            for _ in range(3):
                c.pull()
                c.commit([np.ones_like(x) for x in t])
            c.drain()
        assert _wait_until(lambda: replica._clock >= 3)
        primary.stop()  # the SIGTERM handler's drain
        assert _wait_until(lambda: replica.promoted, timeout=15), \
            "standby never promoted after the primary's clean shutdown"
        # the stream was not torn: the standby holds exactly the acked
        # commits and still serves them
        with PSClient("127.0.0.1", replica.port, templates=t) as c2:
            for g in c2.pull():
                np.testing.assert_allclose(g, np.full_like(g, 3.0))
    finally:
        replica.stop()
        primary.stop()


def test_launcher_sigterm_drains_daemon_cleanly(tmp_path):
    """A real `distkeras-ps` process handles SIGTERM as a graceful drain:
    prints the drain banner, writes --save-final, exits 0."""
    from distkeras_tpu.models.base import Model

    model0 = Model.init(_mlp_spec(), seed=0)
    model_path = str(tmp_path / "model.bin")
    with open(model_path, "wb") as f:
        f.write(model0.serialize())
    final_path = str(tmp_path / "final.bin")
    proc = subprocess.Popen(
        [sys.executable, "-m", "distkeras_tpu.runtime.launcher",
         "--model", model_path, "--port", "0", "--autoscale",
         "--save-final", final_path],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=_REPO_ROOT,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_REPO_ROOT))
    try:
        line = ""
        for _ in range(200):
            line = proc.stdout.readline()
            if not line or "listening" in line:
                break
        assert "listening" in line, f"hub never came up: {line!r}"
        proc.send_signal(signal.SIGTERM)
        out = proc.stdout.read()
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == 0, out
    assert "SIGTERM: draining hub" in out
    assert os.path.exists(final_path), out
    # the drained final model round-trips
    with open(final_path, "rb") as f:
        Model.deserialize(f.read())
