"""Grouped-query attention (GQA, Ainslie et al. 2023).

The oracle: a GQA model is EXACTLY an MHA model whose K/V projection
weights repeat each KV head across its query group — so every GQA test
compares against an MHA twin built by weight repetition, in float32 for
exact equality.  The feature's point (the KV cache shrinking to
num_kv_heads) is asserted directly on cache shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models.base import Model
from distkeras_tpu.models.decode import generate, init_cache, make_generate_fn
from distkeras_tpu.models.transformer import small_lm_spec

H, HKV, D, LAYERS, VOCAB = 4, 2, 32, 2, 61


def _gqa_spec(**kw):
    cfg = dict(vocab_size=VOCAB, model_dim=D, num_heads=H, num_kv_heads=HKV,
               num_layers=LAYERS, max_seq_len=48)
    cfg.update(kw)
    spec = small_lm_spec(**cfg)
    spec.config["compute_dtype"] = "float32"  # exact-oracle tolerances
    return spec


def _mha_twin(gqa_model):
    """MHA model whose fused qkv weights replicate the GQA weights: the
    q slice is the GQA q kernel; the k/v slices repeat each KV head over
    its group.  Forward math is then IDENTICAL to grouped attention."""
    spec = small_lm_spec(vocab_size=VOCAB, model_dim=D, num_heads=H,
                         num_layers=LAYERS, max_seq_len=48)
    spec.config["compute_dtype"] = "float32"
    twin = Model.init(spec, seed=0)
    g = H // HKV
    params = jax.tree.map(np.asarray, twin.params)
    for i in range(LAYERS):
        blk = dict(gqa_model.params[f"block_{i}"])
        qk = np.asarray(blk["q"]["kernel"])          # [E, H, Dh]
        kvk = np.asarray(blk["kv"]["kernel"])        # [E, 2, HKV, Dh]
        fused = np.stack([qk,
                          np.repeat(kvk[:, 0], g, axis=1),
                          np.repeat(kvk[:, 1], g, axis=1)], axis=1)  # [E, 3, H, Dh]
        tb = dict(params[f"block_{i}"])
        tb.pop("qkv")
        tb["qkv"] = {"kernel": fused}
        for name in ("LayerNorm_0", "LayerNorm_1", "proj", "up", "down"):
            tb[name] = jax.tree.map(np.asarray, blk[name])
        params[f"block_{i}"] = tb
    for name in ("embed", "pos_embed", "final_norm"):
        params[name] = jax.tree.map(np.asarray, gqa_model.params[name])
    return Model(spec=spec, params=jax.tree.map(jnp.asarray, params))


@pytest.fixture(scope="module")
def gqa_model():
    return Model.init(_gqa_spec(), seed=3)


def test_param_layout_and_cache_shrink(gqa_model):
    blk = gqa_model.params["block_0"]
    assert "qkv" not in blk and blk["q"]["kernel"].shape == (D, H, D // H)
    assert blk["kv"]["kernel"].shape == (D, 2, HKV, D // H)
    cache = init_cache(dict(gqa_model.spec.config), batch=2, cache_len=32)
    assert cache.k.shape == (LAYERS, 2, 32, HKV, D // H)  # HKV heads, not H
    qcache = init_cache(dict(gqa_model.spec.config), batch=2, cache_len=32,
                        quantized=True)
    assert qcache.k.shape == (LAYERS, 2, 32, HKV, D // H)


def test_forward_matches_mha_twin(gqa_model):
    """Grouped attention == full attention over group-repeated KV weights
    (exact in f32): the one identity that pins the whole feature."""
    twin = _mha_twin(gqa_model)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, VOCAB, (2, 16)),
                       jnp.int32)
    np.testing.assert_allclose(np.asarray(gqa_model.apply(toks)),
                               np.asarray(twin.apply(toks)),
                               rtol=1e-5, atol=1e-5)


def test_decode_matches_mha_twin_and_full_forward(gqa_model):
    """The Hkv-headed cache decode commits the same greedy tokens as the
    MHA twin's full-headed cache decode — and the cache path agrees with
    the no-cache forward (the standard decode-correctness pin)."""
    twin = _mha_twin(gqa_model)
    prompt = jnp.asarray([[5, 17, 3], [40, 2, 21]], jnp.int32)
    got = np.asarray(generate(gqa_model, prompt, max_new_tokens=10))
    want = np.asarray(generate(twin, prompt, max_new_tokens=10))
    np.testing.assert_array_equal(got, want)


def test_quantized_cache_gqa(gqa_model):
    """int8 QKVCache under GQA: per-(position, head) scales quantize the
    same values as the twin's repeated heads, so tokens still match."""
    twin = _mha_twin(gqa_model)
    prompt = jnp.asarray([[9, 9, 10]], jnp.int32)
    got = np.asarray(make_generate_fn(gqa_model.spec, 8, quantize_cache=True)(
        gqa_model.params, prompt))
    want = np.asarray(make_generate_fn(twin.spec, 8, quantize_cache=True)(
        twin.params, prompt))
    np.testing.assert_array_equal(got, want)


def test_quantized_cache_gqa_warns_net_loss(gqa_model):
    """int8 KV x GQA is a measured 13% net loss (94.9k -> 82.4k tok/s at
    b64, BASELINE.md round 5) that composes silently in config — every
    decode builder must emit the documented warning, and must NOT emit it
    for int8-on-MHA or GQA-without-int8 (issue 2 satellite)."""
    import warnings as _warnings

    from distkeras_tpu.models.speculative import make_speculative_generate_fn

    with pytest.warns(UserWarning, match="measured net loss"):
        make_generate_fn(gqa_model.spec, 4, quantize_cache=True)
    # speculative builder routes through the same guard (GQA target)
    draft = Model.init(small_lm_spec(vocab_size=VOCAB, model_dim=D,
                                     num_heads=2, num_layers=1,
                                     max_seq_len=48), seed=9)
    with pytest.warns(UserWarning, match="measured net loss"):
        make_speculative_generate_fn(gqa_model.spec, draft.spec, 4, k=2,
                                     quantize_cache=True)
    # no warning when the trap is absent: MHA + int8, and GQA without int8
    mha = small_lm_spec(vocab_size=VOCAB, model_dim=D, num_heads=H,
                        num_layers=LAYERS, max_seq_len=48)
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        make_generate_fn(mha, 4, quantize_cache=True)
        make_generate_fn(gqa_model.spec, 4)


def test_beam_and_speculative_match_mha_twin(gqa_model):
    """The rest of the serving family rides the same cache math: beam
    search scores and speculative commits equal the MHA twin's."""
    from distkeras_tpu.models.beam import make_beam_search_fn
    from distkeras_tpu.models.speculative import make_speculative_generate_fn

    twin = _mha_twin(gqa_model)
    prompt = jnp.asarray([[5, 17, 3]], jnp.int32)
    g_toks, g_scores = make_beam_search_fn(gqa_model.spec, 6, beam_width=3)(
        gqa_model.params, prompt)
    t_toks, t_scores = make_beam_search_fn(twin.spec, 6, beam_width=3)(
        twin.params, prompt)
    np.testing.assert_array_equal(np.asarray(g_toks), np.asarray(t_toks))
    np.testing.assert_allclose(np.asarray(g_scores), np.asarray(t_scores),
                               rtol=1e-5, atol=1e-5)
    # GQA target with an MHA draft: the committed-token contract holds
    draft = Model.init(small_lm_spec(vocab_size=VOCAB, model_dim=D,
                                     num_heads=2, num_layers=1,
                                     max_seq_len=48), seed=9)
    sfn = make_speculative_generate_fn(gqa_model.spec, draft.spec, 8, k=3)
    got = np.asarray(sfn(gqa_model.params, draft.params, prompt))
    want = np.asarray(generate(gqa_model, prompt, max_new_tokens=8))
    np.testing.assert_array_equal(got, want)


def test_gqa_under_sequence_parallelism():
    """Ring attention with grouped KV: the ICI ring carries Hkv-headed
    blocks; output equals the unsharded forward."""
    from distkeras_tpu.parallel.lm import (lm_data_shardings, lm_state_shardings,
                                           make_lm_train_step, shift_targets)
    from distkeras_tpu.parallel.mesh import create_nd_mesh
    import optax

    mesh = create_nd_mesh((2, 2), ("dp", "sp"))
    spec = small_lm_spec(vocab_size=VOCAB, model_dim=D, num_heads=H,
                         num_kv_heads=HKV, num_layers=2, max_seq_len=16,
                         seq_axis="sp")
    model = Model.init(spec, seed=1)
    opt = optax.sgd(0.05)
    step = make_lm_train_step(spec, opt, mesh, sp_axis="sp")
    psh, osh = lm_state_shardings(mesh, opt, model.params)
    params = jax.device_put(jax.tree.map(jnp.asarray, model.params), psh)
    opt_state = jax.device_put(opt.init(params), osh)
    toks = np.random.default_rng(2).integers(0, VOCAB, (4, 16)).astype(np.int32)
    tgts = shift_targets(toks)
    dsh = lm_data_shardings(mesh, sp_axis="sp")
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state,
                                       jax.device_put(toks, dsh),
                                       jax.device_put(tgts, dsh))
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_gqa_with_tensor_parallelism():
    """tp=2 shards H=4 query heads and HKV=2 kv heads; the step runs and
    learns.  An indivisible kv count fails loudly at module level."""
    from distkeras_tpu.parallel.lm import (lm_data_shardings, lm_state_shardings,
                                           make_lm_train_step, shift_targets)
    from distkeras_tpu.parallel.mesh import create_nd_mesh
    import optax

    mesh = create_nd_mesh((2, 2), ("dp", "tp"))
    spec = small_lm_spec(vocab_size=VOCAB, model_dim=D, num_heads=H,
                         num_kv_heads=HKV, num_layers=2, max_seq_len=16,
                         tp_axis="tp")
    model = Model.init(spec, seed=1)
    opt = optax.sgd(0.05)
    step = make_lm_train_step(spec, opt, mesh, sp_axis=None, tp_axis="tp")
    psh, osh = lm_state_shardings(mesh, opt, model.params, tp_axis="tp")
    params = jax.device_put(jax.tree.map(jnp.asarray, model.params), psh)
    opt_state = jax.device_put(opt.init(params), osh)
    # kv slabs really are distributed over tp
    kvk = params["block_0"]["kv"]["kernel"]
    assert kvk.addressable_shards[0].data.shape[2] == HKV // 2
    toks = np.random.default_rng(2).integers(0, VOCAB, (4, 16)).astype(np.int32)
    tgts = shift_targets(toks)
    dsh = lm_data_shardings(mesh)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state,
                                       jax.device_put(toks, dsh),
                                       jax.device_put(tgts, dsh))
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

    bad = small_lm_spec(vocab_size=VOCAB, model_dim=64, num_heads=4,
                        num_kv_heads=1, num_layers=1, max_seq_len=16,
                        tp_axis="tp")
    from distkeras_tpu.models.base import build_module
    module = build_module(bad.name, dict(bad.config, tp_size=2))
    with pytest.raises(ValueError, match="num_kv_heads"):
        module.init(jax.random.PRNGKey(0),
                    jnp.zeros((1, 16), jnp.int32))


def test_fused_step_refuses_gqa():
    """The fused Pallas decode kernel is MHA-only (v1): auto-select must
    fall back to the XLA step, explicit 'fused' must fail loudly."""
    from distkeras_tpu.ops.decode_step import fused_step_supported, resolve_step_impl

    spec = _gqa_spec(model_dim=128, num_heads=2, num_kv_heads=1)
    cfg = dict(spec.config)
    assert not fused_step_supported(cfg, 1, 256)
    assert resolve_step_impl(cfg, 1, 256, None) == "xla"
