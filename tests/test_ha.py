"""Hub high availability (issue 7): per-shard primary->replica replication
(wire action R), standby promotion behind the clock fence, client failover
address lists, fleet-consistent snapshot sets, and the kill-primary drills.

Every drill is deterministic: kills are scheduled on the hub's commit clock
(:class:`~distkeras_tpu.runtime.faults.HubKillPlan`) or a seeded fault
plan, never on wall-clock sleeps alone.  Drills carry the ``chaos``
marker; the cheapest cell per trainer stays in tier-1, the rest of the
matrix is additionally slow-marked (the PR 6 convention)."""

import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from distkeras_tpu import observability as obs
from distkeras_tpu.runtime.faults import ChaosProxy, HubKillPlan
from distkeras_tpu.runtime.parameter_server import (
    ADAGParameterServer,
    DeltaParameterServer,
    DynSGDParameterServer,
    PSClient,
    ShardedParameterServer,
    ShardedPSClient,
    StripeLostError,
    shard_plan,
)


def _weights():
    return [np.zeros((2, 2), np.float32), np.zeros((3,), np.float32)]


def _ones():
    return [np.ones((2, 2), np.float32), np.ones((3,), np.float32)]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_until(pred, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _replica_pair(hub_cls=DeltaParameterServer, retries=2, backoff=0.05,
                  **primary_kwargs):
    """A started (primary, replica) pair of Python hubs."""
    primary = hub_cls(_weights(), idle_timeout=None, **primary_kwargs)
    primary.start()
    replica = hub_cls(_weights(), idle_timeout=None,
                      replica_of=("127.0.0.1", primary.port),
                      replica_feed_retries=retries,
                      replica_feed_backoff=backoff, **primary_kwargs)
    replica.start()
    return primary, replica


# -- replication stream --------------------------------------------------------

def test_replica_full_syncs_then_tracks_deltas():
    """A standby attaching to a primary with history full-syncs (center +
    clock in one R frame), then applies every subsequent commit's scaled
    delta — its center equals the primary's bit for bit."""
    primary = DeltaParameterServer(_weights(), idle_timeout=None)
    primary.start()
    try:
        with PSClient("127.0.0.1", primary.port, templates=_weights()) as c:
            c.commit(_ones())  # pre-replica history -> exercises full sync
        replica = DeltaParameterServer(
            _weights(), idle_timeout=None,
            replica_of=("127.0.0.1", primary.port))
        replica.start()
        try:
            assert _wait_until(lambda: replica._clock == 1)
            assert replica.is_standby() and not replica.promoted
            with PSClient("127.0.0.1", primary.port,
                          templates=_weights()) as c:
                for _ in range(3):
                    c.commit(_ones())
            assert _wait_until(lambda: replica._clock == 4)
            for a, b in zip(primary.get_weights(), replica.get_weights()):
                np.testing.assert_array_equal(a, b)
            assert replica.num_updates == 4
        finally:
            replica.stop()
    finally:
        primary.stop()


def test_replication_streams_post_aggregation_deltas():
    """The feed carries the APPLIED delta (post scaling rule), not the raw
    commit: an ADAG primary with num_workers=4 streams delta/4, and the
    replica's center matches the primary's exactly — no scaling-rule
    knowledge needed on the replica."""
    primary = ADAGParameterServer(_weights(), num_workers=4,
                                  idle_timeout=None)
    primary.start()
    replica = ADAGParameterServer(_weights(), num_workers=4,
                                  idle_timeout=None,
                                  replica_of=("127.0.0.1", primary.port))
    replica.start()
    try:
        with PSClient("127.0.0.1", primary.port, templates=_weights()) as c:
            for _ in range(4):
                c.commit(_ones())
        assert _wait_until(lambda: replica._clock == 4)
        np.testing.assert_array_equal(replica.get_weights()[0],
                                      np.ones((2, 2), np.float32))
        for a, b in zip(primary.get_weights(), replica.get_weights()):
            np.testing.assert_array_equal(a, b)
    finally:
        replica.stop()
        primary.stop()


def test_replication_is_observationally_pure():
    """Acceptance: with a replica attached but no failure, the PRIMARY's
    center trajectory is bit-identical to an unreplicated run of the same
    commit sequence (x * float32(1.0) and the scale-then-add ordering are
    exact)."""
    rng = np.random.default_rng(7)
    deltas = [[rng.normal(size=w.shape).astype(np.float32) for w in _weights()]
              for _ in range(6)]

    def run(replicated):
        hub = DynSGDParameterServer(_weights(), idle_timeout=None)
        hub.start()
        replica = None
        if replicated:
            replica = DynSGDParameterServer(
                _weights(), idle_timeout=None,
                replica_of=("127.0.0.1", hub.port))
            replica.start()
            assert _wait_until(lambda: hub._feed is not None
                               and hub._feed.active(), timeout=5)
        try:
            with PSClient("127.0.0.1", hub.port, templates=_weights()) as c:
                for d in deltas:
                    c.commit([x.copy() for x in d])
            return [w.copy() for w in hub.get_weights()]
        finally:
            if replica is not None:
                replica.stop()
            hub.stop()

    plain = run(replicated=False)
    replicated = run(replicated=True)
    for a, b in zip(plain, replicated):
        np.testing.assert_array_equal(a, b)


def test_replica_lag_injection_feed_catches_up():
    """Replica-lag injection: the feed routed through a delay-everything
    ChaosProxy tracks the primary with measured lag, then converges."""
    primary = DeltaParameterServer(_weights(), idle_timeout=None)
    primary.start()
    try:
        with ChaosProxy("127.0.0.1", primary.port,
                        delay_all_s=0.05) as proxy:
            replica = DeltaParameterServer(
                _weights(), idle_timeout=None,
                replica_of=("127.0.0.1", proxy.port))
            replica.start()
            try:
                with PSClient("127.0.0.1", primary.port,
                              templates=_weights()) as c:
                    for _ in range(4):
                        c.commit(_ones())
                # commits ack without waiting for the delayed feed hop, so
                # the replica is BEHIND right after the burst...
                assert _wait_until(lambda: replica._clock == 4, timeout=10)
                # ...and converges to the exact primary center
                for a, b in zip(primary.get_weights(),
                                replica.get_weights()):
                    np.testing.assert_array_equal(a, b)
            finally:
                replica.stop()
    finally:
        primary.stop()


def test_publish_out_of_clock_order_loses_nothing():
    """Regression: concurrent commit handlers apply under the hub lock but
    publish under the feed lock, so deltas can reach the feed OUT of clock
    order.  A lower-clock delta arriving behind a higher one must still be
    streamed (deltas commute; only the attach-time sync may filter)."""
    primary, replica = _replica_pair()
    try:
        with PSClient("127.0.0.1", primary.port, templates=_weights()) as c:
            c.commit(_ones())  # ensures the replica is attached + synced
        assert _wait_until(lambda: replica._clock == 1)
        feed = primary._feed
        one = [np.ones_like(t) for t in _weights()]
        # simulate the inversion: clock 3 beats clock 2 to the feed
        feed.publish(3, one)
        feed.publish(2, one)
        assert _wait_until(lambda: replica.num_updates == 3)
        # both deltas landed: center = 3 units, not 2
        np.testing.assert_array_equal(replica.get_weights()[0],
                                      np.full((2, 2), 3, np.float32))
        assert replica._clock == 3
    finally:
        replica.stop()
        primary.stop()


def test_feed_socket_blocks_without_recv_timeout():
    """Regression: the feed's connect timeout must not linger as a recv
    timeout — an idle primary (no commits for 30 s) must not read as feed
    loss and trigger a full-resync loop."""
    primary, replica = _replica_pair()
    try:
        assert _wait_until(lambda: replica._replica_sock is not None)
        assert replica._replica_sock.gettimeout() is None
    finally:
        replica.stop()
        primary.stop()


# -- promotion + fence ---------------------------------------------------------

@pytest.mark.chaos
def test_feed_loss_promotes_behind_clock_fence():
    primary, replica = _replica_pair(retries=2, backoff=0.02)
    try:
        with PSClient("127.0.0.1", primary.port, templates=_weights()) as c:
            for _ in range(3):
                c.commit(_ones())
        assert _wait_until(lambda: replica._clock == 3)
        primary.kill()
        assert _wait_until(lambda: replica.promoted, timeout=10)
        assert not replica.is_standby()
        assert replica._clock_fence == replica._clock == 3
    finally:
        replica.stop()


@pytest.mark.chaos
def test_commit_to_standby_promotes_first():
    """A failed-over worker's commit must not wait for the feed-loss
    detector: committing into a standby promotes it immediately (fence
    armed BEFORE the commit's staleness is computed)."""
    primary, replica = _replica_pair(retries=50, backoff=1.0)  # detector slow
    try:
        with PSClient("127.0.0.1", primary.port, templates=_weights()) as c:
            c.commit(_ones())
        assert _wait_until(lambda: replica._clock == 1)
        primary.kill()
        # the feed notices the death (EOF) almost instantly; a commit
        # arriving even earlier would be refused once as a split-brain
        # probe — wait for the deterministic precondition
        assert _wait_until(lambda: replica._replica_sock is None)
        with PSClient("127.0.0.1", replica.port, templates=_weights()) as c:
            c.commit(_ones())
        assert replica.promoted
        assert replica._clock_fence == 1
        assert replica.num_updates == 2
    finally:
        replica.stop()


@pytest.mark.chaos
def test_promotion_fences_pre_promotion_socket_connections():
    """Regression: a connection born on the STANDBY before promotion
    carries last_pull_clock = the pre-promotion fence (0).  When the hub
    promotes underneath it, its next commit must be re-based at the new
    fence — otherwise DynSGD sees the full replicated clock as staleness
    and near-zeroes the delta."""
    primary, replica = _replica_pair(hub_cls=DynSGDParameterServer,
                                     retries=50, backoff=1.0)
    try:
        with PSClient("127.0.0.1", primary.port, templates=_weights()) as c:
            for _ in range(9):
                c.pull()
                c.commit(_ones())  # staleness 0 each -> center += 1 each
        assert _wait_until(lambda: replica._clock == 9)
        # connection born on the standby BEFORE promotion, never pulls
        early = PSClient("127.0.0.1", replica.port, templates=_weights())
        try:
            primary.kill()
            assert _wait_until(lambda: replica._replica_sock is None)
            # another client's commit promotes (fence = 9, clock -> 10)
            with PSClient("127.0.0.1", replica.port,
                          templates=_weights()) as trigger:
                trigger.commit(_ones())
            assert replica.promoted and replica._clock_fence == 9
            before = replica.get_weights()[0][0, 0]
            early.commit(_ones())  # no pull: stale clock from birth
            after = replica.get_weights()[0][0, 0]
            # fenced: staleness = 10 - 9 = 1 -> scale 1/2.  Unfenced it
            # would be 10 - 0 = 10 -> scale 1/11 (near-zeroed work)
            np.testing.assert_allclose(after - before, 0.5, rtol=1e-6)
        finally:
            early.close()
    finally:
        replica.stop()


@pytest.mark.chaos
def test_commit_with_live_feed_refuses_and_reverifies_no_split_brain():
    """Split-brain guard: one misdirected worker committing into a SYNCED
    standby whose primary is alive must not promote it.  The commit is
    refused and the feed is severed as a probe; the feed reconnects to
    the live primary, the standby stays standby, and the primary keeps
    serving."""
    primary, replica = _replica_pair(retries=5, backoff=0.02)
    try:
        with PSClient("127.0.0.1", primary.port, templates=_weights()) as c:
            c.commit(_ones())
        assert _wait_until(lambda: replica._clock == 1)
        # pulls from a synced standby are fine (read-only)
        with PSClient("127.0.0.1", replica.port, templates=_weights()) as c:
            assert float(c.pull()[0][0, 0]) == 1.0
        # a stray commit while the feed is live: refused, not promoted
        with pytest.raises(ConnectionError):
            with PSClient("127.0.0.1", replica.port,
                          templates=_weights()) as stray:
                stray.commit(_ones())
        assert not replica.promoted
        # the probe severed the feed; it re-verifies the LIVE primary and
        # resyncs — still standby, still tracking
        assert _wait_until(lambda: replica._replica_sock is not None,
                           timeout=10)
        with PSClient("127.0.0.1", primary.port, templates=_weights()) as c:
            c.commit(_ones())
        assert _wait_until(lambda: replica._clock == 2)
        assert replica.is_standby() and not replica.promoted
        for a, b in zip(primary.get_weights(), replica.get_weights()):
            np.testing.assert_array_equal(a, b)
    finally:
        replica.stop()
        primary.stop()


def test_clean_teardown_never_promotes():
    """stop()/kill() of the replica itself is not a failover: the standby
    exits standby-side without promoting."""
    primary, replica = _replica_pair()
    replica.stop()
    assert not replica.promoted
    primary.stop()


# -- client failover -----------------------------------------------------------

@pytest.mark.chaos
def test_client_failover_zero_acked_commit_loss():
    """The acceptance property at the client level: every commit the
    client saw ACKED before the primary's death is present in the
    promoted replica's center (send-to-replica happens before the ack
    leaves); the in-flight unacked commit may drop (PR-4 semantics)."""
    primary, replica = _replica_pair(retries=2, backoff=0.02)
    try:
        # same deterministic gate as the telemetry drill below: the kill
        # must not race the standby's initial attach+sync
        assert replica.wait_synced(timeout=10)
        with PSClient("127.0.0.1", primary.port, templates=_weights(),
                      failover=[("127.0.0.1", replica.port)],
                      max_reconnects=6, reconnect_backoff=0.02) as c:
            acked = 0
            for _ in range(5):
                c.commit(_ones())  # blocking: returns only once acked
                acked += 1
            primary.kill()
            for _ in range(3):
                c.commit(_ones())
            final = [w.copy() for w in c.pull()]
        assert (c.host, c.port) == ("127.0.0.1", replica.port)
        assert replica.promoted
        # zero ACKED loss, judged at PROMOTION time so post-failover
        # commits can't mask a lossy feed: every acked commit replicated
        assert replica.promoted_at_clock >= acked
        # and whatever landed did so exactly once (delta hub: center is an
        # integer multiple of the unit delta)
        assert float(final[0][0, 0]) == replica.num_updates
        assert replica.num_updates <= acked + 3
    finally:
        replica.stop()


@pytest.mark.chaos
def test_failover_telemetry_and_fleet_report():
    """ps.failovers / ps.failover_ms land on a failover (and NOT on a
    same-address reconnect), promotion is counted hub-side, and
    fleet_report surfaces both."""
    primary, replica = _replica_pair(retries=2, backoff=0.02)
    obs.enable()
    obs.reset()
    try:
        # deterministic promotion gate (the PR 8 drill-ordering rule):
        # kill ONLY once the standby has (a) applied its full sync and
        # (b) seen the first commit replicate.  Killing earlier races the
        # replica's initial attach — under full-suite load the standby
        # could still be dialing a primary that is already dead, never
        # sync, and (correctly) refuse to promote forever, so the whole
        # drill came down to thread-scheduling luck (~1-in-10 timeouts)
        assert replica.wait_synced(timeout=10)
        with PSClient("127.0.0.1", primary.port, templates=_weights(),
                      failover=[("127.0.0.1", replica.port)],
                      max_reconnects=6, reconnect_backoff=0.02) as c:
            c.commit(_ones())
            assert _wait_until(lambda: replica._clock >= 1)
            primary.kill()
            c.commit(_ones())
            c.commit(_ones())
        assert _wait_until(lambda: replica.promoted, timeout=10)
        snap = obs.snapshot()
        assert snap["counters"].get("ps.failovers") == 1.0
        hist = snap["histograms"].get("ps.failover_ms")
        assert hist and hist["count"] == 1
        assert snap["counters"].get("ps_promotions_total") == 1.0
        from distkeras_tpu.observability.distributed import fleet_report

        report = fleet_report(events=obs.TRACER.events())
        assert report["failovers_total"] == 1
        assert report["failover_ms_mean"] is not None
        assert len(report["promotions"]) == 1
    finally:
        obs.reset()
        obs.disable()
        replica.stop()


def test_initial_connect_walks_failover_list():
    """A worker (re)started AFTER the failover finds the promoted standby:
    the constructor tries the dead primary, then the failover address."""
    dead_port = _free_port()
    hub = DeltaParameterServer(_weights(), idle_timeout=None)
    hub.start()
    try:
        with PSClient("127.0.0.1", dead_port, templates=_weights(),
                      failover=[("127.0.0.1", hub.port)]) as c:
            assert (c.host, c.port) == ("127.0.0.1", hub.port)
            c.commit(_ones())
        assert hub.num_updates == 1
    finally:
        hub.stop()
    # every address dead -> the primary's error surfaces
    with pytest.raises(OSError):
        PSClient("127.0.0.1", dead_port, templates=_weights(),
                 failover=[("127.0.0.1", _free_port())], timeout=2.0)


# -- heartbeat vs close/failover races (satellite) -----------------------------

@pytest.mark.chaos
def test_heartbeat_racing_reconnect_burns_no_extra_budget():
    """Satellite pin: an aggressive heartbeat riding through a real fault +
    reconnect costs the caller EXACTLY the real fault's budget — the ping
    can neither fire into a half-swapped socket (io-lock serialized) nor
    poison the fresh connection (last_io reset on swap)."""
    from distkeras_tpu.runtime.faults import Fault, FaultPlan

    ps = DeltaParameterServer(_weights(), idle_timeout=None)
    ps.start()
    plan = FaultPlan([Fault(conn=0, direction="s2c", frame=2, kind="sever")])
    try:
        with ChaosProxy("127.0.0.1", ps.port, plan) as proxy:
            with PSClient("127.0.0.1", proxy.port, templates=_weights(),
                          max_reconnects=5, reconnect_backoff=0.02,
                          heartbeat_interval=0.02) as c:
                for _ in range(4):
                    c.pull()
                    c.commit(_ones())
                # idle long enough for many heartbeat rounds on the
                # post-reconnect socket, then keep exchanging
                time.sleep(0.3)
                for _ in range(2):
                    c.pull()
                    c.commit(_ones())
            assert len(proxy.faults_fired) == 1
            assert c.reconnects_used == 1  # the sever, nothing else
    finally:
        ps.stop()


def test_close_during_active_heartbeat_is_clean():
    """close() serializes with the heartbeat under the io lock: repeated
    open/exchange/close cycles with a hot heartbeat never deadlock, leak,
    or consume reconnect budget."""
    ps = DeltaParameterServer(_weights(), idle_timeout=None)
    ps.start()
    try:
        for _ in range(10):
            c = PSClient("127.0.0.1", ps.port, templates=_weights(),
                         max_reconnects=3, reconnect_backoff=0.02,
                         heartbeat_interval=0.01)
            c.pull()
            c.commit(_ones())
            time.sleep(0.02)  # let a ping round trip get going
            c.close()
            assert c.reconnects_used == 0
            assert c._hb_thread is None
    finally:
        ps.stop()


# -- sharded stripes: typed partial failure + per-shard failover ---------------

def _templates():
    return [np.zeros((4, 4), np.float32), np.zeros((8,), np.float32),
            np.zeros((2, 3), np.float32)]


@pytest.mark.chaos
def test_stripe_lost_error_names_the_shard():
    t = _templates()
    plan = shard_plan(t, 2)
    hubs = [DeltaParameterServer(
        [t[i] for i in plan.assignments[sid]], idle_timeout=None,
        shard_id=sid) for sid in range(2)]
    for hub in hubs:
        hub.start()
    obs.enable()
    obs.reset()
    try:
        client = ShardedPSClient(
            [("127.0.0.1", h.port) for h in hubs], t, plan,
            max_reconnects=1, reconnect_backoff=0.02)
        with client:
            client.commit([np.full(a.shape, 0.5, np.float32) for a in t])
            hubs[1].kill()
            with pytest.raises(StripeLostError) as ei:
                for _ in range(3):
                    client.commit([np.full(a.shape, 0.5, np.float32)
                                   for a in t])
        err = ei.value
        assert err.shard_index == 1
        assert f"{err.host}:{err.port}" in str(err)
        assert "shard 1" in str(err)
        assert isinstance(err, ConnectionError)  # old handlers still catch
        spans = [s for s in obs.TRACER.events()
                 if s["name"] == "ps.stripe_lost"]
        assert spans and spans[0]["attrs"]["shard"] == 1
        from distkeras_tpu.observability.distributed import fleet_report

        report = fleet_report(events=obs.TRACER.events())
        assert report["stripes_lost"] and \
            report["stripes_lost"][0]["shard"] == 1
    finally:
        obs.reset()
        obs.disable()
        for hub in hubs:
            hub.stop()


def test_stripe_lost_covers_fail_fast_timeout_and_desync():
    """Regression: with max_reconnects=0 the ORIGINAL fault propagates —
    a recv timeout (socket.timeout, not a ConnectionError) and a desynced
    stream (ProtocolError, a ValueError) must still surface as the typed
    StripeLostError naming the shard."""
    t = _templates()
    plan = shard_plan(t, 2)
    hubs = [DeltaParameterServer(
        [t[i] for i in plan.assignments[sid]], idle_timeout=None,
        shard_id=sid) for sid in range(2)]
    for hub in hubs:
        hub.start()
    try:
        # recv timeout on shard 1: commit, then wait for an ack that a
        # wedged hub never sends (simulated by a tiny client timeout
        # against a hub that DID ack — consume the real ack first via a
        # plain pull... simplest deterministic wedge: point shard 1 at a
        # listener that never replies)
        silent = socket.socket()
        silent.bind(("127.0.0.1", 0))
        silent.listen(8)
        try:
            client = ShardedPSClient(
                [("127.0.0.1", hubs[0].port),
                 ("127.0.0.1", silent.getsockname()[1])],
                t, plan, timeout=0.3, max_reconnects=0)
            with client:
                with pytest.raises(StripeLostError) as ei:
                    client.pull()
            assert ei.value.shard_index == 1
        finally:
            silent.close()
    finally:
        for hub in hubs:
            hub.stop()


@pytest.mark.chaos
def test_sharded_failover_per_stripe():
    """Each shard primary has its own standby; killing ONE shard primary
    fails only that stripe over, and no acked striped commit is lost."""
    t = _templates()
    plan = shard_plan(t, 2)

    def make(sid, replica_of=None):
        hub = DeltaParameterServer(
            [t[i] for i in plan.assignments[sid]], idle_timeout=None,
            shard_id=sid, replica_of=replica_of,
            replica_feed_retries=2, replica_feed_backoff=0.02)
        hub.start()
        return hub

    primaries = [make(sid) for sid in range(2)]
    replicas = [make(sid, replica_of=("127.0.0.1", primaries[sid].port))
                for sid in range(2)]
    try:
        client = ShardedPSClient(
            [("127.0.0.1", h.port) for h in primaries], t, plan,
            max_reconnects=6, reconnect_backoff=0.02,
            failover=[("127.0.0.1", replicas[0].port),
                      ("127.0.0.1", replicas[1].port)])
        with client:
            acked = 0
            for _ in range(4):
                client.commit([np.full(a.shape, 1.0, np.float32) for a in t])
                acked += 1
            assert _wait_until(lambda: all(r._clock == acked
                                           for r in replicas))
            primaries[1].kill()
            for _ in range(3):
                client.commit([np.full(a.shape, 1.0, np.float32) for a in t])
            final = [w.copy() for w in client.pull()]
        assert replicas[1].promoted
        assert not replicas[0].promoted          # stripe 0 never failed over
        assert client.shards[0].reconnects_used == 0
        assert (client.shards[1].host, client.shards[1].port) == \
            ("127.0.0.1", replicas[1].port)
        # shard 0 (untouched primary) saw all 7; shard 1's standby holds
        # at least every acked striped commit
        assert primaries[0].num_updates == 7
        assert replicas[1].num_updates >= acked
        for i in plan.assignments[1]:
            assert float(np.ravel(final[i])[0]) == replicas[1].num_updates
    finally:
        for hub in replicas + primaries:
            try:
                hub.stop()
            except Exception:
                pass


# -- coordinated snapshot sets -------------------------------------------------

def _facade(tmp_path, hub_cls=DeltaParameterServer, native=False, **kw):
    t = _templates()
    plan = shard_plan(t, 2)
    if native:
        from distkeras_tpu.runtime.native import (MODE_DELTA,
                                                  NativeParameterServer)

        def factory(w, sid):
            return NativeParameterServer(w, mode=MODE_DELTA,
                                         idle_timeout=None, shard_id=sid)
    else:
        def factory(w, sid):
            return hub_cls(w, idle_timeout=None, shard_id=sid)
    ps = ShardedParameterServer(t, plan, factory,
                                snapshot_dir=str(tmp_path), **kw)
    return ps, plan, t


@pytest.mark.parametrize("hub_kind", ["python", "native"])
def test_snapshot_set_saves_one_causal_cut_and_restores(tmp_path, hub_kind):
    if hub_kind == "native":
        from distkeras_tpu.runtime.native import native_available
        if not native_available():
            pytest.skip("no C++ toolchain for the native hub")
    ps, plan, t = _facade(tmp_path, native=(hub_kind == "native"),
                          snapshot_interval=3600.0)
    ps.start()
    try:
        for hub in ps.shards:
            assert getattr(hub, "snapshotter", None) is None
        ps.commit_direct([np.full(a.shape, 0.5, np.float32) for a in t], 0)
        ps.coordinator.save_set()
        expected = [w.copy() for w in ps.get_weights()]
        # set metadata: same set id + clock vector everywhere
        metas = [cp.metadata()["metadata"] for cp in ps.coordinator.checkpointers]
        assert len({m["snapshot_set"] for m in metas}) == 1
        assert all(m["set_clocks"] == [1, 1] for m in metas)
    finally:
        ps.kill()  # crash semantics: recovery comes from the snapshot set

    fresh, _, _ = _facade(tmp_path, native=(hub_kind == "native"),
                          snapshot_interval=3600.0, restore=True)
    fresh.start()
    try:
        for a, b in zip(expected, fresh.get_weights()):
            np.testing.assert_array_equal(a, b)
        if hub_kind == "python":
            for hub in fresh.shards:
                assert hub._clock_fence == hub._clock == 1
    finally:
        fresh.stop()


@pytest.mark.parametrize("hub_kind", ["python", "native"])
def test_torn_snapshot_set_detected_and_refused(tmp_path, hub_kind):
    """Satellite: a multi-shard restore across mismatched sets must be
    detected — fall back to the newest COMPLETE set when one exists,
    refuse when none does.  Covers both hubs."""
    if hub_kind == "native":
        from distkeras_tpu.runtime.native import native_available
        if not native_available():
            pytest.skip("no C++ toolchain for the native hub")
    ps, plan, t = _facade(tmp_path, native=(hub_kind == "native"),
                          snapshot_interval=3600.0)
    ps.start()
    try:
        ps.commit_direct([np.full(a.shape, 0.5, np.float32) for a in t], 0)
        ps.coordinator.save_set()          # step 1: complete
        set1 = [w.copy() for w in ps.get_weights()]
        ps.commit_direct([np.full(a.shape, 0.5, np.float32) for a in t], 0)
        ps.coordinator.save_set()          # step 2: will be torn below
    finally:
        ps.kill()

    # tear step 2: shard 1's copy vanishes (crash between per-shard saves)
    ps.coordinator.checkpointers[1].delete_step(2)

    fresh, _, _ = _facade(tmp_path, native=(hub_kind == "native"),
                          snapshot_interval=3600.0, restore=True)
    with pytest.warns(UserWarning, match="torn"):
        fresh.start()  # falls back to the newest COMPLETE set (step 1)
    try:
        for a, b in zip(set1, fresh.get_weights()):
            np.testing.assert_array_equal(a, b)
    finally:
        fresh.kill()

    # mismatched-clock tear: shard 1's step-1 snapshot replaced by one
    # from a DIFFERENT history (wrong set id + wrong clock) -> with no
    # complete set left anywhere, restore must refuse
    rogue = DeltaParameterServer([t[i] for i in plan.assignments[1]],
                                 idle_timeout=None)
    center, state = rogue.snapshot_state()
    ps.coordinator.checkpointers[1].delete_step(1)
    ps.coordinator.checkpointers[1].save(
        1, {"center": center}, metadata={"kind": "ps-hub-snapshot", **state})
    last, _, _ = _facade(tmp_path, native=(hub_kind == "native"),
                         snapshot_interval=3600.0, restore=True)
    with pytest.warns(UserWarning):
        with pytest.raises(RuntimeError, match="complete and clock-consistent"):
            last.start()


def test_legacy_per_shard_snapshots_restore_with_torn_warning(tmp_path):
    """Back-compat: shard-NN/ snapshots written by PR-6's independent
    per-shard snapshotters carry no snapshot_set id.  The coordinated
    restore path must still load them (warning about the uncoordinated
    cut) instead of stranding the job behind the torn-set refusal."""
    t = _templates()
    plan = shard_plan(t, 2)
    # write PR-6-style snapshots: per-hub snapshotters, no coordination
    hubs = [DeltaParameterServer(
        [t[i] for i in plan.assignments[sid]], idle_timeout=None,
        shard_id=sid, snapshot_dir=os.path.join(str(tmp_path),
                                                f"shard-{sid:02d}"),
        snapshot_interval=3600.0) for sid in range(2)]
    legacy = ShardedParameterServer(t, plan, lambda w, sid: hubs[sid])
    legacy.start()
    try:
        legacy.commit_direct([np.full(a.shape, 0.5, np.float32)
                              for a in t], 0)
        for hub in legacy.shards:
            hub.snapshotter.save_now()
        expected = [w.copy() for w in legacy.get_weights()]
    finally:
        legacy.kill()

    fresh, _, _ = _facade(tmp_path, snapshot_interval=3600.0, restore=True)
    with pytest.warns(UserWarning, match="predates coordinated sets"):
        fresh.start()
    try:
        for a, b in zip(expected, fresh.get_weights()):
            np.testing.assert_array_equal(a, b)
        for hub in fresh.shards:
            assert hub._clock_fence == hub._clock == 1
    finally:
        fresh.stop()


def test_snapshot_set_gc_prunes_all_shards_in_lockstep(tmp_path):
    """Satellite: keep-N retention applies to the SET — after every save,
    all shard-NN/ directories hold exactly the same step numbers."""
    ps, plan, t = _facade(tmp_path, snapshot_interval=3600.0,
                          snapshot_keep=2)
    ps.start()
    try:
        for _ in range(4):
            ps.commit_direct([np.full(a.shape, 0.5, np.float32)
                              for a in t], 0)
            ps.coordinator.save_set()
        step_sets = [cp.all_steps() for cp in ps.coordinator.checkpointers]
        assert step_sets[0] == step_sets[1] == [3, 4]
    finally:
        ps.kill()


def test_launcher_facade_uses_coordinated_snapshots(tmp_path):
    """start_parameter_server's all-shards-in-one-process path snapshots
    through the coordinator (per-hub snapshotters stay off), and a
    relaunch with restore=True resumes the set."""
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.runtime.launcher import start_parameter_server

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,),
                                         "num_outputs": 2},
                     input_shape=(8,))
    model = Model.init(spec, seed=0)
    snap = str(tmp_path / "sets")
    ps = start_parameter_server(model, mode="delta", num_shards=2,
                                idle_timeout=None, snapshot_dir=snap,
                                snapshot_interval=3600.0)
    try:
        assert ps.coordinator is not None
        assert all(getattr(h, "snapshotter", None) is None
                   for h in ps.shards)
        ps.commit_direct([np.ones(w.shape, np.float32)
                          for w in ps.get_weights()], 0)
    finally:
        ps.stop()  # writes the final coordinated set
    expected_first = None
    ps2 = start_parameter_server(model, mode="delta", num_shards=2,
                                 idle_timeout=None, snapshot_dir=snap,
                                 snapshot_interval=3600.0, restore=True)
    try:
        got = ps2.get_weights()
        expected_first = float(np.ravel(got[0])[0])
        assert ps2.num_updates == 1
    finally:
        ps2.stop()
    assert expected_first is not None


# -- launcher / trainer replica plumbing ---------------------------------------

def test_launcher_replica_of_starts_a_tracking_standby():
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.runtime.launcher import start_parameter_server

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,),
                                         "num_outputs": 2},
                     input_shape=(8,))
    model = Model.init(spec, seed=0)
    primary = start_parameter_server(model, mode="delta", idle_timeout=None)
    replica = start_parameter_server(model, mode="delta", idle_timeout=None,
                                     replica_of=("127.0.0.1", primary.port))
    try:
        assert replica.is_standby()
        primary.commit_direct([np.ones(w.shape, np.float32)
                               for w in primary.get_weights()], 0)
        assert _wait_until(lambda: replica._clock == 1)
        for a, b in zip(primary.get_weights(), replica.get_weights()):
            np.testing.assert_array_equal(a, b)
    finally:
        replica.stop()
        primary.stop()
    # native hubs run the replication feed too since ISSUE 11 (both
    # sides); the cross-implementation drills live in test_native_ps.py


def test_native_hub_accepts_replica_of():
    """replica_of on the C++ hub constructs a standby (ISSUE 11) — the
    live feed/promotion drills ride tests/test_native_ps.py."""
    from distkeras_tpu.runtime.native import (MODE_DELTA,
                                              NativeParameterServer,
                                              native_available)

    if not native_available():
        pytest.skip("no C++ toolchain for the native hub")
    ps = NativeParameterServer(_weights(), mode=MODE_DELTA,
                               replica_of=("127.0.0.1", 1))
    assert ps.is_standby() and not ps.promoted


def test_trainer_replica_knob_validation():
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import ModelSpec

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,),
                                         "num_outputs": 2},
                     input_shape=(8,))
    with pytest.raises(ValueError, match="worker-only"):
        dk.AsyncADAG(spec, ps_address=("h", 1), replica_of=("h", 2))
    with pytest.raises(ValueError, match="num_shards"):
        dk.AsyncADAG(spec, num_shards=2, replica_of=("h", 2))
    with pytest.raises(ValueError, match="per shard"):
        dk.AsyncADAG(spec, ps_address=[("h", 1), ("h", 2)],
                     ps_failover=[("h", 3)])
    # a bare pair with num_shards=2 has the RIGHT length by accident and
    # must still be rejected, not sliced into per-shard garbage
    with pytest.raises(ValueError, match="single \\(host, port\\) pair"):
        dk.AsyncADAG(spec, ps_address=[("h", 1), ("h", 2)],
                     ps_failover=("127.0.0.1", 6000))
    tr = dk.AsyncADAG(spec, ps_address=("h", 1), ps_failover=("h", 2))
    assert tr._ps_failover == [[("h", 2)]]


@pytest.mark.chaos
def test_trainer_replica_of_takes_over_primary_state():
    """A trainer whose own hub is a replica_of standby must WAIT for the
    primary's full sync before its workers run: training continues from
    the primary's center (here: far from init), never silently from
    seed."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.runtime.launcher import start_parameter_server

    model0 = Model.init(_mlp_spec(), seed=0)
    primary = start_parameter_server(model0, mode="adag", num_workers=2,
                                     idle_timeout=None)
    # move the primary's center somewhere unmistakable (the adag hub
    # halves the delta at num_workers=2 — read back what actually landed)
    primary.commit_direct([np.full(w.shape, 7.25, np.float32) - w
                           for w in primary.get_weights()], 0)
    marker = [w.copy() for w in primary.get_weights()]
    assert not np.allclose(marker[0], 0.0)
    trainer = dk.AsyncADAG(Model.init(_mlp_spec(), seed=0),
                           loss="categorical_crossentropy", batch_size=16,
                           num_epoch=1, num_workers=2,
                           communication_window=2, learning_rate=0.0,
                           seed=0, replica_of=("127.0.0.1", primary.port))
    try:
        model = trainer.train(_tiny_dataset())
    finally:
        primary.stop()
    hub = trainer.parameter_server
    assert hub.promoted  # the first worker commit took the job over
    # lr=0 -> every commit delta is zero: the final center IS the synced
    # primary center, proving workers trained from it, not from seed
    from distkeras_tpu.utils import flatten_weights

    final, _ = flatten_weights(model.params)
    for f, m in zip(final, marker):
        np.testing.assert_allclose(np.asarray(f), m, atol=1e-6)


def test_trainer_replica_of_unreachable_primary_fails_loudly():
    """replica_of pointing at a dead address must raise, not silently
    train from fresh weights (and a never-synced standby never promotes
    itself meanwhile)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model

    dead = _free_port()
    trainer = dk.AsyncADAG(Model.init(_mlp_spec(), seed=0),
                           loss="categorical_crossentropy", batch_size=16,
                           num_epoch=1, num_workers=1,
                           communication_window=2, learning_rate=0.05,
                           seed=0, replica_of=("127.0.0.1", dead),
                           replica_sync_timeout=1.0)
    with pytest.raises(RuntimeError, match="no full sync"):
        trainer.train(_tiny_dataset())


def test_commit_into_never_synced_standby_is_refused():
    """A standby whose sync never arrived holds fresh init weights, not
    the job's state: a commit into it (a worker failing over too eagerly)
    must be refused — the connection drops and the standby stays
    unpromoted — instead of promoting seed weights into 'the job'."""
    dead = _free_port()
    replica = DeltaParameterServer(_weights(), idle_timeout=None,
                                   replica_of=("127.0.0.1", dead),
                                   replica_feed_retries=1000,
                                   replica_feed_backoff=0.05)
    replica.start()
    try:
        with pytest.raises(ConnectionError):
            with PSClient("127.0.0.1", replica.port,
                          templates=_weights()) as c:
                c.commit(_ones())
        assert not replica.promoted
        assert replica.is_standby()
        assert replica.num_updates == 0
        # pulls are refused too: seed weights must never be served as if
        # they were the job's state (a failed-over worker would train a
        # whole window on them)
        with pytest.raises(ConnectionError):
            with PSClient("127.0.0.1", replica.port,
                          templates=_weights()) as c:
                c.pull()
        # inproc paths refuse too, with guidance
        with pytest.raises(RuntimeError, match="never-synced standby"):
            replica.commit_direct(_ones(), 0)
        with pytest.raises(RuntimeError, match="never-synced standby"):
            replica.pull_direct()
    finally:
        replica.stop()


def test_never_synced_standby_does_not_promote():
    """A standby that never reached its primary keeps retrying (one
    warning, capped backoff) instead of promoting — it has nothing to
    take over, and serving fresh init weights as the job's state would be
    silent data loss."""
    dead = _free_port()
    replica = DeltaParameterServer(_weights(), idle_timeout=None,
                                   replica_of=("127.0.0.1", dead),
                                   replica_feed_retries=1,
                                   replica_feed_backoff=0.02)
    with pytest.warns(UserWarning, match="never-synced standby"):
        replica.start()
        # well past the retry budget: still standby, still unpromoted
        time.sleep(0.5)
        assert replica.is_standby() and not replica.promoted
        replica.stop()


# -- kill-primary-mid-run drills (the acceptance matrix) -----------------------

_TRAINER_MODES = {
    "AsyncDOWNPOUR": "delta",
    "AsyncADAG": "adag",
    "AsyncDynSGD": "dynsgd",
    "AsyncAEASGD": "delta",
    "AsyncEAMSGD": "delta",
}


def _tiny_dataset(n=256, seed=0):
    from distkeras_tpu.data.dataset import Dataset

    rng = np.random.default_rng(seed)
    half = n // 2
    x = np.concatenate([
        rng.normal(loc=-2.0, scale=1.0, size=(half, 8)),
        rng.normal(loc=+2.0, scale=1.0, size=(half, 8))]).astype(np.float32)
    y = np.concatenate([np.zeros(half, np.int64), np.ones(half, np.int64)])
    perm = rng.permutation(n)
    return Dataset({"features": x[perm],
                    "label": np.eye(2, dtype=np.float32)[y[perm]]})


def _mlp_spec():
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(name="mlp", config={"hidden_sizes": (16,),
                                         "num_outputs": 2},
                     input_shape=(8,))


def _kill_primary_drill(trainer_name, pipeline=True, after_commits=8):
    """One kill-primary drill: external primary + hot standby, a trainer
    in worker-only mode with the standby as its failover address, the
    primary crashed on its commit clock mid-run."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.runtime.launcher import start_parameter_server

    model0 = Model.init(_mlp_spec(), seed=0)
    mode = _TRAINER_MODES[trainer_name]
    primary = start_parameter_server(model0, mode=mode, num_workers=2,
                                     idle_timeout=None)
    replica = start_parameter_server(model0, mode=mode, num_workers=2,
                                     idle_timeout=None,
                                     replica_of=("127.0.0.1", primary.port))
    kill_plan = HubKillPlan(after_commits=after_commits)
    try:
        kwargs = dict(loss="categorical_crossentropy", batch_size=16,
                      num_epoch=2, num_workers=2, communication_window=2,
                      learning_rate=0.05, seed=0, pipeline=pipeline,
                      ps_address=("127.0.0.1", primary.port),
                      ps_failover=("127.0.0.1", replica.port),
                      max_reconnects=8, reconnect_backoff=0.02)
        if trainer_name in ("AsyncAEASGD", "AsyncEAMSGD"):
            kwargs["rho"] = 2.0
        trainer = getattr(dk, trainer_name)(Model.init(_mlp_spec(), seed=0),
                                            **kwargs)
        kill_plan.start(primary)
        model = trainer.train(_tiny_dataset())
        kill_plan.join()
        assert kill_plan.fired.is_set(), "primary was never killed"
        assert replica.promoted, "standby never promoted"
        assert trainer.worker_errors == []
        assert len(trainer.history) > 0
        assert np.isfinite(trainer.history).all()
        # zero ACKED loss, judged at PROMOTION time (end-of-run counts are
        # inflated by post-failover commits): at the kill, at most
        # num_workers * max_inflight_commits commits were
        # applied-but-unacked; every acked one must have replicated
        slack = trainer.num_workers * trainer.max_inflight_commits
        assert replica.promoted_at_clock is not None
        assert replica.promoted_at_clock >= kill_plan.fired_at_clock - slack
        # post-failover progress actually landed on the standby
        assert replica.num_updates > replica.promoted_at_clock
        assert model.predict(_tiny_dataset()["features"][:4]).shape == (4, 2)
        return trainer
    finally:
        kill_plan.cancel()
        replica.stop()
        try:
            primary.stop()
        except Exception:
            pass


@pytest.mark.chaos
@pytest.mark.parametrize("pipeline", [True, False])
def test_kill_primary_mid_run_failover_adag(pipeline):
    """Tier-1 drill cell (cheapest trainer config, both exchange modes):
    workers fail over to the standby within the reconnect budget and the
    run completes with zero acked-commit loss."""
    _kill_primary_drill("AsyncADAG", pipeline=pipeline)


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("trainer_name",
                         ["AsyncDOWNPOUR", "AsyncDynSGD", "AsyncAEASGD",
                          "AsyncEAMSGD"])
def test_kill_primary_mid_run_failover_matrix(trainer_name):
    """The rest of the trainer matrix (slow-marked, PR-6 convention)."""
    _kill_primary_drill(trainer_name)


@pytest.mark.chaos
@pytest.mark.slow
def test_kill_primary_sigkill_subprocess(tmp_path):
    """The deployment-shaped drill: a REAL distkeras-ps primary process
    SIGKILLed mid-run, a distkeras-ps --replica-of standby in-process
    promoting, workers failing over.  Slow-marked: subprocess startup
    pays full import twice."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.runtime.launcher import start_parameter_server

    model0 = Model.init(_mlp_spec(), seed=0)
    model_path = str(tmp_path / "model.bin")
    with open(model_path, "wb") as f:
        f.write(model0.serialize())
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "distkeras_tpu.runtime.launcher",
         "--model", model_path, "--mode", "adag", "--num-workers", "2",
         "--port", str(port), "--idle-timeout", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=repo_root,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root))
    line = ""
    for _ in range(200):
        line = proc.stdout.readline()
        if not line or "listening" in line:
            break
    assert "listening" in line, f"primary never came up: {line!r}"
    replica = start_parameter_server(model0, mode="adag", num_workers=2,
                                     idle_timeout=None,
                                     replica_of=("127.0.0.1", port))
    result = {}

    def run_trainer():
        trainer = dk.AsyncADAG(
            Model.init(_mlp_spec(), seed=0),
            loss="categorical_crossentropy", batch_size=16, num_epoch=3,
            num_workers=2, communication_window=2, learning_rate=0.05,
            seed=0, ps_address=("127.0.0.1", port),
            ps_failover=("127.0.0.1", replica.port),
            max_reconnects=20, reconnect_backoff=0.05)
        trainer.train(_tiny_dataset())
        result["history"] = trainer.history

    t = threading.Thread(target=run_trainer)
    t.start()
    try:
        assert _wait_until(lambda: replica._clock >= 4, timeout=120.0)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        t.join(timeout=300)
        assert not t.is_alive(), "trainer did not finish after failover"
        assert len(result.get("history", [])) > 0
        assert replica.promoted
    finally:
        replica.stop()
        if proc.poll() is None:
            proc.kill()
