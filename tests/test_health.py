"""Live fleet health plane (ISSUE 8): sliding-window time series on the
metrics registry, the streaming collector (wire action ``M``), the online
detectors, ``distkeras-top`` rendering, and the wire-compat /
coverage-verdict satellites.

The acceptance drill at the bottom (chaos-marked) runs real PS workers
with one ChaosProxy-delayed straggler and one HubKillPlan'd primary, and
asserts both HealthEvents — straggler naming the delayed worker, failover
naming the promoted standby — are visible DURING the run through the
punchcard ``fetch_telemetry(..., health=True)`` pull.
"""

import json
import threading
import time

import numpy as np
import pytest

from distkeras_tpu import observability as obs
from distkeras_tpu.observability import health as health_mod
from distkeras_tpu.observability.health import (
    HealthCollector,
    HealthMonitor,
    render_top,
)
from distkeras_tpu.observability.metrics import MetricsRegistry, TimeSeries


@pytest.fixture
def telemetry():
    obs.reset()
    health_mod.reset_default()
    obs.enable()
    yield obs
    obs.disable()
    obs.reset()
    health_mod.reset_default()


@pytest.fixture
def fresh_health():
    """Clean process-default collector/monitor without enabling the
    registry (the health plane works with telemetry off — it has its own
    opt-in)."""
    health_mod.reset_default()
    yield health_mod
    health_mod.reset_default()


def _weights():
    return [np.zeros((2, 2), np.float32), np.zeros((3,), np.float32)]


def _ones():
    return [np.ones((2, 2), np.float32), np.ones((3,), np.float32)]


def _wait_until(pred, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# -- TimeSeries ----------------------------------------------------------------

def test_timeseries_validation():
    with pytest.raises(ValueError):
        TimeSeries(window_s=0)
    with pytest.raises(ValueError):
        TimeSeries(max_samples=1)
    with pytest.raises(ValueError):
        TimeSeries(kind="nope")


def test_timeseries_window_prune_and_cap():
    s = TimeSeries(window_s=10.0, max_samples=4)
    for i in range(6):
        s.append(float(i), ts=100.0 + i)
    # ring cap: only the newest 4 survive
    assert [v for _, v in s.samples(now=105.0)] == [2.0, 3.0, 4.0, 5.0]
    # window prune: at now=114.5 only ts >= 104.5 qualify
    assert [v for _, v in s.samples(now=114.5)] == [5.0]
    # fully expired window -> empty, reducers go None (not zero)
    assert s.samples(now=200.0) == []
    assert s.rate(now=200.0) is None
    assert s.mean(now=200.0) is None


def test_timeseries_cumulative_rate_is_value_delta():
    s = TimeSeries(window_s=60.0, kind="cumulative")
    s.append(100.0, ts=10.0)
    s.append(140.0, ts=20.0)
    assert s.rate(now=20.0) == pytest.approx(4.0)  # 40 over 10 s
    # single sample: no interval -> None
    s2 = TimeSeries(kind="cumulative")
    s2.append(5.0, ts=1.0)
    assert s2.rate(now=1.0) is None


def test_timeseries_sample_rate_is_samples_per_second():
    s = TimeSeries(window_s=60.0, kind="sample")
    for i in range(5):
        s.append(123.0, ts=float(i))  # 5 samples over 4 s
    assert s.rate(now=4.0) == pytest.approx(1.0)


def test_timeseries_mean_percentile_ewma_last():
    s = TimeSeries(window_s=60.0)
    for i, v in enumerate([1.0, 2.0, 3.0, 4.0, 100.0]):
        s.append(v, ts=float(i))
    assert s.last() == 100.0
    assert s.mean(now=4.0) == pytest.approx(22.0)
    assert s.percentile(50, now=4.0) == 3.0
    assert s.percentile(95, now=4.0) == 100.0
    assert s.percentile(0, now=4.0) == 1.0
    # EWMA weights the newest heaviest: far above the plain median
    assert s.ewma(now=4.0) > 30.0


def test_timeseries_summary_shapes():
    s = TimeSeries(window_s=30.0, kind="sample")
    assert s.summary() == {"n": 0, "window_s": 30.0, "kind": "sample"}
    s.append(2.0, ts=1.0)
    s.append(4.0, ts=2.0)
    out = s.summary(now=2.0)
    assert out["n"] == 2 and out["last"] == 4.0
    assert {"rate", "mean", "p50", "p95", "ewma"} <= set(out)
    c = TimeSeries(kind="cumulative")
    c.append(1.0, ts=1.0)
    c.append(3.0, ts=2.0)
    cs = c.summary(now=2.0)
    assert cs["rate"] == pytest.approx(2.0)
    assert "p95" not in cs  # quantiles of a running total are meaningless
    json.dumps(out), json.dumps(cs)  # JSON-safe contract


# -- registry track / tracked_snapshot ----------------------------------------

def test_track_attaches_series_to_existing_and_future_instruments():
    reg = MetricsRegistry(enabled=True)
    pre = reg.counter("c_total")           # exists before track()
    reg.track("c_total", window_s=30.0, max_samples=8)
    post = reg.counter("c_total", shard="1")  # created after track()
    pre.inc()
    post.inc(2)
    assert pre.series is not None and len(pre.series) == 1
    assert post.series is not None and len(post.series) == 1
    assert pre.series.kind == "cumulative"
    snap = reg.tracked_snapshot()
    assert set(snap) == {"c_total", 'c_total{shard="1"}'}
    assert snap["c_total"]["last"] == 1.0
    # untracked instruments never appear
    reg.gauge("depth").set(3)
    assert "depth" not in reg.tracked_snapshot()


def test_untrack_detaches_and_retrack_resets():
    reg = MetricsRegistry(enabled=True)
    reg.track("g", window_s=60.0)
    g = reg.gauge("g")
    g.set(1.0)
    assert len(reg.series("g")) == 1
    reg.untrack("g")
    assert reg.series("g") is None
    g.set(2.0)  # no series attached: only the is-None branch runs
    reg.track("g", window_s=5.0, max_samples=16)
    assert len(reg.series("g")) == 0  # fresh ring, new params
    assert reg.series("g").window_s == 5.0
    assert g.value == 2.0  # lifetime value untouched throughout


def test_tracked_histogram_window_quantiles_are_exact():
    """The ring keeps raw observations, so rolling p95 is exact — tighter
    than the lifetime histogram's log-bucket resolution."""
    reg = MetricsRegistry(enabled=True)
    reg.track("lat_ms")
    h = reg.histogram("lat_ms")
    for v in [10.0, 11.0, 12.0, 13.0, 500.0]:
        h.observe(v)
    assert h.series.percentile(50) == 12.0
    assert h.series.percentile(95) == 500.0
    # observe_n lands ONE window sample per bulk replay, not n
    h.observe_n(7.0, 100)
    assert len(h.series) == 6


def test_untrack_racing_mutation_never_raises():
    """untrack() nulls inst.series under the REGISTRY lock only; every
    mutator must read self.series ONCE (local binding) or a concurrent
    untrack turns the second read into an AttributeError inside e.g. a
    hub commit path."""
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("c_total")
    g = reg.gauge("g")
    h = reg.histogram("h_ms")
    errors = []
    stop = threading.Event()

    def hammer():
        try:
            while not stop.is_set():
                c.inc()
                g.set(1.0)
                h.observe(2.0)
        except Exception as e:  # pragma: no cover - the regression
            errors.append(e)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for _ in range(300):
            for name in ("c_total", "g", "h_ms"):
                reg.track(name, window_s=5.0, max_samples=8)
            for name in ("c_total", "g", "h_ms"):
                reg.untrack(name)
    finally:
        stop.set()
        t.join(timeout=10)
    assert errors == []


def test_disabled_registry_appends_no_samples():
    reg = MetricsRegistry(enabled=False)
    reg.track("c_total")
    c = reg.counter("c_total")
    c.inc()
    assert len(c.series) == 0


def test_registry_reset_clears_samples_keeps_tracking():
    reg = MetricsRegistry(enabled=True)
    reg.track("c_total")
    c = reg.counter("c_total")
    c.inc()
    reg.reset()
    assert c.value == 0.0
    assert len(c.series) == 0
    c.inc()  # tracking registration survived the reset
    assert len(c.series) == 1


def test_obs_facade_track_series_and_snapshot(telemetry):
    obs.track("ps_commits_total", window_s=15.0)
    obs.counter("ps_commits_total").inc(3)
    s = obs.series("ps_commits_total")
    assert s is not None and s.last() == 3.0
    assert "ps_commits_total" in obs.tracked_snapshot()
    obs.untrack("ps_commits_total")
    assert obs.series("ps_commits_total") is None


# -- dual clock stamps (satellite 1) ------------------------------------------

def test_snapshot_carries_wall_and_monotonic_stamps():
    reg = MetricsRegistry(enabled=True)
    reg.counter("c_total").inc()
    a = reg.snapshot()
    b = reg.snapshot()
    assert abs(a["ts_wall"] - time.time()) < 60.0
    assert b["ts_monotonic"] >= a["ts_monotonic"]
    # exact rate denominator: dt from the monotonic pair is well-defined
    assert isinstance(a["ts_monotonic"], float)


def test_jsonl_flusher_records_both_clocks_and_series(tmp_path):
    from distkeras_tpu.observability.sinks import JsonlFlusher

    reg = MetricsRegistry(enabled=True)
    reg.track("c_total")
    reg.counter("c_total").inc(2)
    path = tmp_path / "metrics.jsonl"
    f = JsonlFlusher(str(path), reg, interval=60.0)
    f.flush()
    f.flush()
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(lines) == 2
    for rec in lines:
        assert "ts" in rec and "ts_monotonic" in rec
        assert "ts_wall" in rec["metrics"] and "ts_monotonic" in rec["metrics"]
        assert rec["series"]["c_total"]["last"] == 2.0
    assert lines[1]["ts_monotonic"] >= lines[0]["ts_monotonic"]


# -- HealthCollector -----------------------------------------------------------

def _report(worker, seq=0, **metrics):
    return {"job": "j1", "worker": worker, "seq": seq,
            "t_wall": time.time(), "metrics": metrics}


def test_collector_ingest_builds_per_worker_series():
    c = HealthCollector()
    c.ingest(_report(0, seq=0, windows_total=4.0, window_wall_ms=12.0),
             shard=1)
    c.ingest(_report(0, seq=1, windows_total=8.0, window_wall_ms=14.0),
             shard=1)
    assert c.workers() == ["0"]
    assert c.series("0", "windows_total").kind == "cumulative"
    assert c.series("0", "window_wall_ms").kind == "sample"
    meta = c.meta("0")
    assert meta["reports"] == 2 and meta["seq"] == 1
    assert meta["shard"] == 1 and meta["job"] == "j1"
    snap = c.snapshot()
    json.dumps(snap)
    entry = snap["workers"]["0"]
    assert entry["metrics"]["windows_total"]["last"] == 8.0
    assert entry["meta"]["age_s"] is not None
    assert snap["n_workers"] == 1


def test_collector_drops_malformed_and_none_valued():
    c = HealthCollector()
    c.ingest({"metrics": {"x": 1.0}})               # no worker key
    c.ingest({"worker": 0, "metrics": "garbage"})   # metrics not a dict
    c.ingest({"worker": 1, "metrics": {"a": "NaN-ish", "b": None}})
    assert c.workers() == []  # nothing landed, nothing raised


def test_collector_observe_direct_fold():
    c = HealthCollector()
    c.observe("3", "staleness", 2.0, shard=0, ts=10.0)
    c.observe("3", "staleness", 5.0, shard=0, ts=11.0)
    s = c.series("3", "staleness")
    assert [v for _, v in s.samples(now=11.0)] == [2.0, 5.0]
    assert c.meta("3")["shard"] == 0


# -- HealthMonitor detectors ---------------------------------------------------

def _fed_monitor(**kw):
    c = HealthCollector(window_s=300.0)
    kw.setdefault("cooldown_s", 0.0)
    return c, HealthMonitor(c, **kw)


def test_straggler_detector_names_slow_worker():
    c, m = _fed_monitor(straggler_factor=2.0, min_fleet=3, min_samples=3)
    now = time.monotonic()
    for w in ("0", "1", "2"):
        for i in range(3):
            c.observe(w, "window_wall_ms", 10.0, ts=now - 3 + i)
    for i in range(3):
        c.observe("3", "window_wall_ms", 50.0, shard=0, ts=now - 3 + i)
    events = m.check(now)
    assert [e.kind for e in events] == ["straggler"]
    ev = events[0]
    assert ev.worker == "3" and ev.shard == 0
    assert ev.evidence["factor"] >= 2.0
    # below min_fleet: no verdict at all (a 2-worker "median" is noise)
    c2, m2 = _fed_monitor(min_fleet=3)
    for i in range(3):
        c2.observe("0", "window_wall_ms", 10.0, ts=now - 3 + i)
        c2.observe("1", "window_wall_ms", 90.0, ts=now - 3 + i)
    assert m2.check(now) == []


def test_staleness_spike_detector_needs_spike_and_floor():
    c, m = _fed_monitor(staleness_factor=3.0, staleness_min=4.0)
    now = time.monotonic()
    for i, v in enumerate([1.0, 1.0, 1.0, 1.0, 9.0]):
        c.observe("2", "staleness", v, ts=now - 5 + i)
    events = m.check(now)
    assert [e.kind for e in events] == ["staleness_spike"]
    assert events[0].worker == "2"
    assert events[0].evidence["staleness"] == 9.0
    # same shape but under the absolute floor: small-number noise, silent
    c2, m2 = _fed_monitor(staleness_factor=3.0, staleness_min=4.0)
    for i, v in enumerate([0.1, 0.1, 0.1, 0.1, 3.5]):
        c2.observe("2", "staleness", v, ts=now - 5 + i)
    assert m2.check(now) == []


def test_storm_detectors_fire_on_window_growth():
    c, m = _fed_monitor(storm_threshold=3)
    now = time.monotonic()
    c.observe("1", "reconnects_total", 0.0, ts=now - 4)
    c.observe("1", "reconnects_total", 3.0, ts=now - 1)
    c.observe("2", "failovers_total", 1.0, ts=now - 4)
    c.observe("2", "failovers_total", 4.0, ts=now - 1)
    kinds = sorted(e.kind for e in m.check(now))
    assert kinds == ["failover_storm", "reconnect_storm"]
    assert all(e.severity == "critical" for e in m.check(now)) or True


def test_cumulative_rate_and_increase_survive_counter_reset():
    """An elastic worker restart re-enters its cumulative counters at
    zero: rate()/increase() must read the reset as a reset (Prometheus
    semantics — post-reset value counts as growth), never as a huge
    negative delta that corrupts the throughput baseline."""
    s = TimeSeries(window_s=300.0, kind="cumulative")
    for ts, v in ((0.0, 10.0), (1.0, 200.0), (2.0, 1.0), (3.0, 5.0)):
        s.append(v, ts=ts)
    # growth = (200-10) + reset-to-1 + (5-1) = 195, over dt=3
    assert s.increase(now=3.0) == 195.0
    assert s.rate(now=3.0) == pytest.approx(195.0 / 3.0)
    # sample-kind series have no increase semantics
    assert TimeSeries(kind="sample").increase() is None


def test_tracked_counter_concurrent_incs_stay_monotonic():
    """Samples append INSIDE the instrument lock: concurrent incs landing
    out of order would read as counter resets to the reset-aware
    reducers, inflating increase()/rate() by the full counter value."""
    reg = MetricsRegistry(enabled=True)
    reg.track("c_total", window_s=300.0, max_samples=8192)
    counter = reg.counter("c_total")
    threads = [threading.Thread(
        target=lambda: [counter.inc() for _ in range(500)])
        for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = counter.series
    values = [v for _, v in s.samples()]
    assert all(b >= a for a, b in zip(values, values[1:])), "out-of-order"
    assert s.increase() == values[-1] - values[0]
    assert counter.value == 2000.0


def test_storm_detector_fires_across_counter_reset():
    """A reconnect storm straddling a worker restart (counter back to
    zero mid-window) must still sum to a storm, not read as negative
    growth and mask itself."""
    c, m = _fed_monitor(storm_threshold=3)
    now = time.monotonic()
    for i, v in enumerate([1.0, 3.0, 1.0, 2.0]):  # restart after 3
        c.observe("1", "reconnects_total", v, ts=now - 4 + i)
    events = m.check(now)
    assert [e.kind for e in events] == ["reconnect_storm"]
    # (3-1) + reset-to-1 + (2-1): the naive last-first delta reads 1
    assert events[0].evidence["count"] == 4.0


def test_replication_lag_detector_requires_growth_and_floor():
    c, m = _fed_monitor(lag_growth_factor=2.0, lag_min=8.0)
    now = time.monotonic()
    for i, v in enumerate([2.0, 2.0, 9.0, 11.0]):
        c.observe("hub0", "replication_lag", v, ts=now - 4 + i)
    events = m.check(now)
    assert [e.kind for e in events] == ["replication_lag"]
    # large but FLAT lag: not a growth signal
    c2, m2 = _fed_monitor(lag_growth_factor=2.0, lag_min=8.0)
    for i in range(4):
        c2.observe("hub0", "replication_lag", 20.0, ts=now - 4 + i)
    assert m2.check(now) == []


def test_throughput_regression_fires_after_frozen_baseline():
    c, m = _fed_monitor(throughput_drop=0.5, baseline_checks=2)
    t0 = time.monotonic()
    # healthy phase: ~10 windows/s fleet-wide
    for i in range(4):
        c.observe("0", "windows_total", 10.0 * i, ts=t0 - 10 + i)
    assert m.check(t0 - 6) == []   # baseline settling (check 1)
    assert m.check(t0 - 6) == []   # baseline frozen  (check 2)
    # collapse: the same counter barely advances over the recent window
    for i in range(4):
        c.observe("0", "windows_total", 40.0 + 0.1 * i, ts=t0 + i)
    # old fast samples age out of the 300 s window?  No — rate() spans the
    # whole window, so feed enough slow samples that the delta collapses
    c_new = HealthCollector(window_s=8.0)
    m_new = HealthMonitor(c_new, cooldown_s=0.0, throughput_drop=0.5,
                          baseline_checks=1)
    for i in range(4):
        c_new.observe("0", "windows_total", 10.0 * i, ts=t0 + i)
    assert m_new.check(t0 + 3) == []  # freezes baseline ~10/s
    for i in range(4):
        c_new.observe("0", "windows_total", 30.0 + 0.1 * i, ts=t0 + 10 + i)
    events = m_new.check(t0 + 13)
    assert [e.kind for e in events] == ["throughput_regression"]
    assert events[0].evidence["windows_per_s"] < 5.0


def test_cooldown_suppresses_repeat_and_emit_pipeline(tmp_path, telemetry):
    c = HealthCollector()
    path = tmp_path / "health.jsonl"
    m = HealthMonitor(c, cooldown_s=60.0, jsonl_path=str(path))
    ev = m.emit("failover", "critical", worker="4", shard=1,
                promoted="127.0.0.1:9999")
    assert ev is not None
    assert m.emit("failover", worker="4") is None        # cooled down
    assert m.emit("failover", worker="5") is not None    # different key
    events = m.events()
    assert len(events) == 2 and events[0]["kind"] == "failover"
    assert events[0]["evidence"]["promoted"] == "127.0.0.1:9999"
    # JSONL sink: one line per event, durable even if nobody polls
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert [rec["worker"] for rec in lines] == ["4", "5"]
    # span ring: the PR-5 pipeline carries health events as spans
    spans = [e for e in obs.TRACER.events() if e["name"] == "health.event"]
    assert len(spans) == 2
    assert spans[0]["attrs"]["kind"] == "failover"
    assert spans[0]["attrs"]["ev_promoted"] == "127.0.0.1:9999"


def test_emit_dedup_separates_worker_less_sources():
    """Four untraced clients failing over in one process are four events:
    the cooldown key extends by ``dedup`` so worker-less events from
    DISTINCT sources each record, while the same source re-firing within
    the cooldown is still suppressed."""
    c = HealthCollector()
    m = HealthMonitor(c, cooldown_s=60.0)
    for i in range(4):
        assert m.emit("failover", "critical", dedup=f"client:{i}",
                      to_addr="h:1") is not None
    # a promotion is a different source again — not collapsed either
    assert m.emit("failover", "critical", dedup="promote:h:1",
                  promoted="h:1") is not None
    # the SAME source inside the cooldown is suppressed
    assert m.emit("failover", "critical", dedup="client:0") is None
    assert len(m.events()) == 5


def test_maybe_check_is_rate_limited():
    c = HealthCollector()
    m = HealthMonitor(c, check_interval_s=3600.0)
    now = time.monotonic()
    m.maybe_check(now)
    calls = []
    m.check = lambda n=None: calls.append(n) or []
    m.maybe_check(now + 1.0)         # inside the interval: no check
    assert calls == []
    m.maybe_check(now + 3601.0)      # past it: runs
    assert len(calls) == 1


def test_one_broken_detector_does_not_silence_others():
    c, m = _fed_monitor(storm_threshold=1)
    now = time.monotonic()
    c.observe("1", "reconnects_total", 0.0, ts=now - 2)
    c.observe("1", "reconnects_total", 5.0, ts=now - 1)
    m._detect_stragglers = lambda now: (_ for _ in ()).throw(RuntimeError)
    kinds = [e.kind for e in m.check(now)]
    assert "reconnect_storm" in kinds


# -- render_top / distkeras-top ------------------------------------------------

def test_render_top_table_and_events():
    c = HealthCollector()
    now = time.monotonic()
    for i in range(3):
        c.observe("0", "window_wall_ms", 12.0, ts=now - 3 + i)
        c.observe("0", "windows_total", 10.0 * i, ts=now - 3 + i)
    c.observe("0", "staleness", 2.0, ts=now)
    m = HealthMonitor(c, cooldown_s=0.0)
    m.emit("straggler", worker="0", window_wall_ms=44.0)
    frame = render_top({"fleet": c.snapshot(), "events": m.events()})
    assert "WORKER" in frame and "WIN/S" in frame
    lines = frame.splitlines()
    row = next(line for line in lines if line.strip().startswith("0 "))
    assert "12.0" in row
    assert any("straggler" in line and "worker=0" in line for line in lines)
    # numeric worker ids sort numerically, not lexically
    c.observe("10", "windows_total", 1.0)
    c.observe("2", "windows_total", 1.0)
    frame2 = render_top({"fleet": c.snapshot(), "events": []})
    order = [line.split()[0] for line in frame2.splitlines()[2:]]
    assert order == ["0", "2", "10"]


def test_render_top_empty_is_safe():
    frame = render_top({})
    assert "0 worker(s)" in frame


def test_transport_meta_folds_and_renders_trans_column():
    """ISSUE 18: a report's ``transport`` field lands in worker meta,
    shows up in distkeras-top's TRANS column, and feeds fleet_report's
    transport block; workers that never report one render "-" and a
    transport-free fleet carries no block at all."""
    from distkeras_tpu.observability.distributed import fleet_report

    c = HealthCollector()
    c.ingest({"worker": "0", "transport": "shm", "job": "expA",
              "metrics": {"windows_total": 3.0}})
    c.ingest({"worker": "1", "transport": "tcp", "job": "expB",
              "metrics": {"windows_total": 3.0}})
    c.ingest({"worker": "2", "metrics": {"windows_total": 1.0}})
    assert c.meta("0")["transport"] == "shm"
    assert "transport" not in c.meta("2")
    frame = render_top({"fleet": c.snapshot(), "events": []})
    assert "TRANS" in frame.splitlines()[1]
    # JOB + fleet-size columns (ISSUE 19): row layout is
    # WORKER JOB SHARD TRANS ...; the title counts workers and jobs
    assert "JOB" in frame.splitlines()[1]
    assert "fleet 3 worker(s), 2 job(s)" in frame.splitlines()[0]
    rows = {line.split()[0]: line for line in frame.splitlines()[2:]}
    assert rows["0"].split()[1] == "expA"
    assert rows["2"].split()[1] == "-"
    assert rows["0"].split()[3] == "shm"
    assert rows["1"].split()[3] == "tcp"
    assert rows["2"].split()[3] == "-"

    report = fleet_report(events=[], live=c)
    assert report["transport"] == {
        "workers": {"0": "shm", "1": "tcp"},
        "counts": {"shm": 1, "tcp": 1}}
    # absent-case byte-identity: no transport meta -> no block
    c2 = HealthCollector()
    c2.ingest({"worker": "0", "metrics": {"windows_total": 1.0}})
    assert "transport" not in fleet_report(events=[], live=c2)


# -- punchcard pull + console e2e ---------------------------------------------

def test_punchcard_health_pull_and_top_console(telemetry, capsys):
    from distkeras_tpu.runtime.job_deployment import Punchcard, fetch_telemetry

    c = health_mod.collector()
    now = time.monotonic()
    for i in range(3):
        c.observe("7", "window_wall_ms", 21.0, ts=now - 3 + i)
    health_mod.monitor().emit("straggler", worker="7", window_wall_ms=21.0)
    pc = Punchcard(secret="s3cret").start()
    try:
        resp = fetch_telemetry("127.0.0.1", pc.port, "s3cret", health=True)
        assert resp["health"]["fleet"]["workers"]["7"]["metrics"][
            "window_wall_ms"]["mean"] == pytest.approx(21.0)
        assert resp["health"]["events"][0]["kind"] == "straggler"
        # a plain telemetry pull does NOT compute the health view
        bare = fetch_telemetry("127.0.0.1", pc.port, "s3cret")
        assert "health" not in bare
        # the console binary renders the same pull (one frame, no clear)
        health_mod.main(["--port", str(pc.port), "--secret", "s3cret",
                         "--iterations", "1", "--no-clear"])
    finally:
        pc.stop()
    out = capsys.readouterr().out
    assert "distkeras-top" in out and "straggler" in out


# -- wire action M: streaming collector over sockets ---------------------------

def test_report_health_over_socket_lands_in_hub_collector(fresh_health):
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    ps = DeltaParameterServer(_weights(), port=0, idle_timeout=None)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights()) as c:
            c.report_health(_report(3, windows_total=4.0, window_wall_ms=9.0))
            # the report's ack coalesces like a commit ack; a blocking op
            # after it proves the stream stayed in sync
            c.commit(_ones())
            c.report_health(_report(3, seq=1, windows_total=8.0,
                                    window_wall_ms=11.0))
            c.drain()
        col = health_mod.collector()
        assert _wait_until(lambda: (col.meta("3") or {}).get("reports") == 2)
        assert col.series("3", "windows_total").last() == 8.0
        assert col.series("3", "window_wall_ms").mean() == pytest.approx(10.0)
    finally:
        ps.stop()


def test_malformed_health_frame_does_not_kill_connection(fresh_health):
    from distkeras_tpu.runtime import networking as net
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    ps = DeltaParameterServer(_weights(), port=0, idle_timeout=None)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights()) as c:
            with c._io_lock:
                net.send_frame(c.sock, net.encode_health_payload(
                    b"{not json"))
                c._pending.append((net.ACTION_ACK, time.perf_counter()))
            c.commit(_ones())  # connection still healthy
        assert ps.num_updates == 1
        assert health_mod.collector().workers() == []
    finally:
        ps.stop()


def test_broken_ingest_does_not_kill_connection(fresh_health, monkeypatch):
    """The handler's guard is broad, not a type list: ANY exception out
    of the ingest/detector path (broken detector, full-disk sink, a bug)
    must be swallowed — health can never take down a training
    connection."""
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    ps = DeltaParameterServer(_weights(), port=0, idle_timeout=None)

    def boom(report):
        raise RuntimeError("detector exploded")

    monkeypatch.setattr(ps, "_ingest_health", boom)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights()) as c:
            c.report_health(_report(0, windows_total=1.0))
            c.commit(_ones())  # connection still healthy
            c.drain()
        assert ps.num_updates == 1
    finally:
        ps.stop()


def test_ingest_after_any_shard_prebind_binds_monitor(fresh_health):
    """_observe_health's any_shard path pre-binds _health without a
    monitor; the first wire report afterwards must bind the monitor
    independently instead of dereferencing None (which would tear down
    the reporting worker's connection)."""
    from distkeras_tpu.runtime.parameter_server import DeltaParameterServer

    hub = DeltaParameterServer(_weights())
    c = health_mod.collector()  # plane active
    hub._observe_health("hub", "replication_lag", 1.0, any_shard=True)
    assert hub._health is c and hub._health_monitor is None
    hub._ingest_health({"worker": "4", "metrics": {"windows_total": 1.0}})
    assert hub._health_monitor is health_mod.monitor()
    assert c.series("4", "windows_total") is not None


def test_cooldown_map_stays_bounded_under_client_churn():
    """Per-client dedup keys churn with an elastic fleet: entries past
    the cooldown are pruned once the map is large, so a long-lived hub
    does not leak one key per short-lived client forever."""
    c = HealthCollector()
    m = HealthMonitor(c, cooldown_s=0.0, capacity=8)
    for i in range(1500):
        m.emit("failover", dedup=f"client:{i}")
    assert len(m._last_fired) < 1100


def test_commit_staleness_joins_worker_series_once_health_active(telemetry):
    """Hub-side fold: once ANY report armed the hub's collector, every
    context-announced commit's staleness lands in that worker's series."""
    from distkeras_tpu.observability import distributed as dtrace
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    ps = DeltaParameterServer(_weights(), port=0, idle_timeout=None)
    ps.start()
    try:
        ctx = dtrace.TraceContext(job_id="j", worker_id=5,
                                  span_id=dtrace.new_span_id())
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      trace_context=ctx) as c:
            c.report_health(_report(5, windows_total=1.0))
            c.pull()
            c.commit(_ones())
            c.drain()
        col = health_mod.collector()
        assert _wait_until(lambda: col.series("5", "staleness") is not None)
        assert col.series("5", "staleness").last() == 0.0
    finally:
        ps.stop()


def test_observe_health_shard_gate_and_any_shard(fresh_health, monkeypatch):
    """Worker-keyed hub folds count once per LOGICAL commit (shard 0
    only), but series whose KEY carries the shard — the hub's own
    replication-lag pseudo-worker — must flow from EVERY shard via
    ``any_shard=True``.  A shard-N hub never ingests wire reports (they
    ride shard 0), so its any_shard fold must LAZILY join an
    already-active process plane — and must NOT activate one itself."""
    from distkeras_tpu.runtime.parameter_server import DeltaParameterServer

    hub = DeltaParameterServer(_weights(), shard_id=1)
    assert hub._health is None
    # plane never activated in this process: the fold stays a no-op and
    # does not conjure a collector into existence
    monkeypatch.setattr(health_mod, "_collector", None)
    monkeypatch.setattr(health_mod, "_monitor", None)
    hub._observe_health("hub1", "replication_lag", 5.0, any_shard=True)
    assert hub._health is None and health_mod.active_collector() is None
    # plane active (some worker reported → shard 0 created the default
    # collector): the shard-1 hub's fold binds to it THROUGH the real
    # path, no manual _health assignment
    c = health_mod.collector()
    hub._observe_health("hub1", "replication_lag", 5.0, any_shard=True)
    assert hub._health is c
    assert c.series("hub1", "replication_lag").last() == 5.0
    assert c.meta("hub1")["shard"] == 1
    # worker-keyed folds stay shard-0-only even with _health bound
    hub._observe_health("0", "staleness", 2.0)
    assert c.series("0", "staleness") is None
    hub0 = DeltaParameterServer(_weights(), shard_id=0)
    hub0._health = c
    hub0._observe_health("0", "staleness", 2.0)
    assert c.series("0", "staleness").last() == 2.0


def test_inproc_report_health_folds_directly(fresh_health):
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        InprocPSClient,
    )

    ps = DeltaParameterServer(_weights(), port=0, idle_timeout=None)
    client = InprocPSClient(ps, templates=_weights())
    client.report_health(_report(2, windows_total=3.0))
    col = health_mod.collector()
    assert col.meta("2")["reports"] == 1
    assert client.reconnects_used == 0 and client.failovers_used == 0


def test_sharded_report_health_travels_shard_zero_only(fresh_health):
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        ShardedPSClient,
        ShardedParameterServer,
        shard_plan,
    )

    t = [np.zeros((4, 4), np.float32), np.zeros((6,), np.float32),
         np.zeros((3,), np.float32)]
    plan = shard_plan(t, 2)
    ps = ShardedParameterServer(
        t, plan, lambda w, sid: DeltaParameterServer(
            w, shard_id=sid, idle_timeout=None))
    ps.start()
    try:
        addrs = [("127.0.0.1", p) for p in ps.ports]
        with ShardedPSClient(addrs, t, plan) as c:
            c.report_health(_report(1, windows_total=2.0))
            c.drain()
        col = health_mod.collector()
        assert _wait_until(lambda: (col.meta("1") or {}).get("reports") == 1)
        # the fold is attributed to shard 0 (the one-logical-report rule)
        assert col.meta("1")["shard"] == 0
    finally:
        ps.stop()


# -- wire compatibility (satellite 3: the PR-5 T-matrix, for action M) ---------

class _RecordingSock:
    """Transparent socket wrapper recording every byte the client sends —
    the compat matrix compares these streams across hub generations."""

    def __init__(self, sock):
        self._sock = sock
        self.tx = bytearray()

    def sendall(self, data):
        self.tx += bytes(data)
        return self._sock.sendall(data)

    def __getattr__(self, name):
        return getattr(self._sock, name)


def _client_session_bytes(port, templates):
    """One canonical pull+commit+pull session of an un-upgraded client
    (no trace context, no health reports), returning the exact bytes it
    put on the wire."""
    from distkeras_tpu.runtime.parameter_server import PSClient

    with PSClient("127.0.0.1", port, templates=templates) as c:
        rec = _RecordingSock(c.sock)
        c.sock = rec
        c.pull()
        c.commit([np.full_like(t, 0.5) for t in templates])
        c.pull()
        c.drain()
    return bytes(rec.tx)


def test_plain_client_bytes_identical_against_health_collecting_hub(
        fresh_health):
    """Un-upgraded client vs health-collecting hub: the session's byte
    stream equals the same session against a hub that never saw a health
    report — action M is invisible unless spoken."""
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    t = _weights()
    plain = DeltaParameterServer(t, port=0, idle_timeout=None)
    plain.start()
    collecting = DeltaParameterServer(t, port=0, idle_timeout=None)
    collecting.start()
    try:
        # arm the second hub's collector: another (upgraded) worker reports
        with PSClient("127.0.0.1", collecting.port, templates=t) as c:
            c.report_health(_report(9, windows_total=1.0))
            c.drain()
        assert _wait_until(lambda: collecting._health is not None)
        baseline = _client_session_bytes(plain.port, t)
        against_collecting = _client_session_bytes(collecting.port, t)
    finally:
        plain.stop()
        collecting.stop()
    assert baseline == against_collecting
    # and the stream never contains an M frame (upgraded-client-vs-old-hub
    # direction: a client that does not report sends the pre-M protocol,
    # so a pre-M hub never sees an unknown action)
    from distkeras_tpu.runtime import networking as net

    assert net.encode_health_payload(b"{}")[:1] == net.ACTION_HEALTH
    assert baseline == _strip_no_m(baseline)


def _strip_no_m(stream: bytes) -> bytes:
    """Walk the length-prefixed frames, asserting none carries action M."""
    from distkeras_tpu.runtime import networking as net

    out = bytearray()
    i = 0
    while i < len(stream):
        n = int.from_bytes(stream[i:i + 8], "big")
        frame = stream[i:i + 8 + n]
        assert frame[8:9] != net.ACTION_HEALTH
        out += frame
        i += 8 + n
    return bytes(out)


def test_plain_striped_client_bytes_identical_on_health_collecting_shards(
        fresh_health):
    """The sharded cell of the compat matrix: per-stripe byte streams of
    an un-upgraded striped worker are identical whether or not shard 0's
    collector is active."""
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        ShardedPSClient,
        ShardedParameterServer,
        shard_plan,
    )

    t = [np.zeros((4, 4), np.float32), np.zeros((6,), np.float32),
         np.zeros((3,), np.float32)]
    plan = shard_plan(t, 2)

    def make():
        ps = ShardedParameterServer(
            t, plan, lambda w, sid: DeltaParameterServer(
                w, shard_id=sid, idle_timeout=None))
        ps.start()
        return ps

    def session(ps):
        with ShardedPSClient([("127.0.0.1", p) for p in ps.ports],
                             t, plan) as c:
            recs = []
            for sc in c.shards:
                rec = _RecordingSock(sc.sock)
                sc.sock = rec
                recs.append(rec)
            c.pull()
            c.commit([np.full_like(a, 0.5) for a in t])
            c.pull()
            c.drain()
        return [bytes(r.tx) for r in recs]

    plain, collecting = make(), make()
    try:
        from distkeras_tpu.runtime.parameter_server import PSClient

        with PSClient("127.0.0.1", collecting.ports[0],
                      templates=[t[i] for i in plan.assignments[0]]) as c:
            c.report_health(_report(9, windows_total=1.0))
            c.drain()
        assert _wait_until(lambda: collecting.shards[0]._health is not None)
        base_streams = session(plain)
        coll_streams = session(collecting)
    finally:
        plain.stop()
        collecting.stop()
    assert base_streams == coll_streams
    for s in base_streams:
        _strip_no_m(s)


def test_plain_client_bytes_identical_on_replicated_hub(fresh_health):
    """The replicated cell: a primary streaming to a hot standby serves an
    un-upgraded client the same byte conversation as an unreplicated hub
    (health plane armed on the primary, for good measure)."""
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    t = _weights()
    plain = DeltaParameterServer(t, port=0, idle_timeout=None)
    plain.start()
    primary = DeltaParameterServer(t, port=0, idle_timeout=None)
    primary.start()
    replica = DeltaParameterServer(
        t, idle_timeout=None, replica_of=("127.0.0.1", primary.port))
    replica.start()
    try:
        with PSClient("127.0.0.1", primary.port, templates=t) as c:
            c.report_health(_report(9, windows_total=1.0))
            c.drain()
        assert _wait_until(lambda: primary._health is not None)
        baseline = _client_session_bytes(plain.port, t)
        against_primary = _client_session_bytes(primary.port, t)
    finally:
        replica.stop()
        primary.stop()
        plain.stop()
    assert baseline == against_primary


def test_replication_lag_folds_with_registry_disabled(fresh_health):
    """The replication-lag fold must ride the health plane's OWN opt-in,
    not the registry flag: a replicated hub with DKT_TELEMETRY unset but
    workers reporting health must still feed the replication_lag series
    the lag-growth detector reads."""
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    assert not obs.enabled()
    t = _weights()
    primary = DeltaParameterServer(t, port=0, idle_timeout=None)
    primary.start()
    replica = DeltaParameterServer(
        t, idle_timeout=None, replica_of=("127.0.0.1", primary.port))
    replica.start()
    try:
        assert replica.wait_synced(timeout=10)
        with PSClient("127.0.0.1", primary.port, templates=t) as c:
            # the report activates the plane on the primary; the commits
            # then publish replication frames whose lag must fold
            c.report_health(_report(0, windows_total=1.0))
            for _ in range(3):
                c.commit(_ones())
            c.drain()
        col = health_mod.collector()
        assert _wait_until(
            lambda: col.series("hub", "replication_lag") is not None), \
            "no replication_lag series with registry disabled"
    finally:
        replica.stop()
        primary.stop()


def test_health_ack_not_a_commit_latency_sample(telemetry):
    """A health report's ack must not land in ps.commit_latency_ms or
    hold a max_inflight commit slot — only commits are commit latency."""
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    t = _weights()
    ps = DeltaParameterServer(t, port=0, idle_timeout=None)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=t,
                      max_inflight=1) as c:
            # interleave: with max_inflight=1, a health ack counted as a
            # commit would make the second report/commit pair block on an
            # already-consumed slot; and each report would add a latency
            # sample
            for i in range(3):
                c.report_health(_report(0, seq=i, windows_total=float(i)))
                c.commit_nowait(_ones())
            assert c._unacked() <= 1
            c.drain()
        snap = obs.REGISTRY.snapshot()
        assert snap["histograms"]["ps.commit_latency_ms"]["count"] == 3
    finally:
        ps.stop()


# -- fleet_report coverage verdict (satellite 2) -------------------------------

def test_fleet_report_empty_inputs_yield_explicit_empty(telemetry):
    from distkeras_tpu.observability.distributed import fleet_report

    report = fleet_report(events=[])
    cov = report["coverage"]
    assert cov["status"] == "empty"
    assert cov["spans"] == 0
    assert any("no spans" in r for r in cov["reasons"])
    assert report["workers"] == {}


def test_fleet_report_zero_span_trace_dir(telemetry, tmp_path):
    from distkeras_tpu.observability.distributed import fleet_report

    report = fleet_report(trace_dir=str(tmp_path))
    assert report["coverage"]["status"] == "empty"


def test_fleet_report_windows_without_commits_is_partial(telemetry):
    from distkeras_tpu.observability.distributed import fleet_report

    with obs.span("async.window", worker=0):
        pass
    report = fleet_report()
    cov = report["coverage"]
    assert cov["status"] == "partial"
    assert cov["window_spans"] == 1 and cov["commits"] == 0
    assert any("no ps.handle_commit" in r for r in cov["reasons"])


def test_fleet_report_commits_without_context_is_partial(telemetry):
    from distkeras_tpu.observability.distributed import fleet_report

    obs.TRACER.record_span("ps.handle_commit", 1_000, 2_000, staleness=1)
    report = fleet_report()
    cov = report["coverage"]
    assert cov["status"] == "partial"
    assert any("no worker context" in r for r in cov["reasons"])


def test_fleet_report_live_single_sample_flags_insufficient(telemetry):
    from distkeras_tpu.observability.distributed import fleet_report

    c = HealthCollector()
    c.observe("0", "windows_total", 1.0)
    report = fleet_report(events=[], live=c)
    cov = report["coverage"]
    # spans are empty but the collector holds a worker: partial, not empty
    assert cov["status"] == "partial"
    assert cov["live_workers"] == 1
    assert cov["live_insufficient"] == ["0"]
    assert any("< 2 samples" in r for r in cov["reasons"])
    assert report["live"]["workers"]["0"]["metrics"]["windows_total"]["n"] == 1


def test_fleet_report_empty_live_collector_does_not_degrade_ok(telemetry):
    """Health reporting is opt-in: a COMPLETE span join polled through
    the punchcard (which always passes the process collector) must read
    ``ok``, not permanently ``partial``, when no health report ever
    arrived.  The empty collector only names itself when there are no
    spans either (where it explains the emptiness)."""
    from distkeras_tpu.observability.distributed import fleet_report

    with obs.span("async.window", worker=0):
        pass
    obs.TRACER.record_span("ps.handle_commit", 1_000, 2_000,
                           worker=0, staleness=1)
    report = fleet_report(live=HealthCollector())
    assert report["coverage"]["status"] == "ok"
    assert report["coverage"]["live_workers"] == 0
    # no spans AND no live workers: empty, with the collector named
    report2 = fleet_report(events=[], live=HealthCollector())
    assert report2["coverage"]["status"] == "empty"
    assert any("no health report" in r for r in report2["coverage"]["reasons"])


def test_fleet_report_joined_run_is_ok(telemetry):
    from distkeras_tpu.observability.distributed import fleet_report

    with obs.span("async.window", worker=0):
        pass
    obs.TRACER.record_span("ps.handle_commit", 1_000, 2_000,
                           worker=0, staleness=1)
    c = HealthCollector()
    now = time.monotonic()
    c.observe("0", "windows_total", 1.0, ts=now - 1)
    c.observe("0", "windows_total", 2.0, ts=now)
    report = fleet_report(live=c)
    assert report["coverage"]["status"] == "ok"
    assert report["coverage"]["reasons"] == []
    assert report["live"]["workers"]["0"]["metrics"]["windows_total"]["n"] == 2


def test_fleet_report_live_collector_failure_degrades(telemetry):
    from distkeras_tpu.observability.distributed import fleet_report

    class Broken:
        def snapshot(self):
            raise RuntimeError("half-built")

    report = fleet_report(events=[], live=Broken())
    assert "live" not in report
    assert report["coverage"]["status"] == "empty"


# -- zero-cost-when-off guards -------------------------------------------------

def test_health_off_makes_zero_collector_calls(fresh_health, monkeypatch):
    """The acceptance guard: with telemetry off AND no health_interval_s,
    a full socket exchange makes zero registry AND zero collector calls."""
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    obs.disable()
    calls = []
    monkeypatch.setattr(HealthCollector, "ingest",
                        lambda self, *a, **k: calls.append("ingest"))
    monkeypatch.setattr(HealthCollector, "observe",
                        lambda self, *a, **k: calls.append("observe"))
    monkeypatch.setattr(HealthMonitor, "emit",
                        lambda self, *a, **k: calls.append("emit"))
    orig_get = MetricsRegistry._get

    def counting_get(self, kind, name, labels):
        calls.append(("reg", name))
        return orig_get(self, kind, name, labels)

    monkeypatch.setattr(MetricsRegistry, "_get", counting_get)
    t = _weights()
    ps = DeltaParameterServer(t, port=0, idle_timeout=None)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=t) as c:
            for _ in range(3):
                c.pull()
                c.commit(_ones())
            c.drain()
    finally:
        ps.stop()
    assert calls == [], f"health/registry touched while off: {calls[:5]}"
    assert ps._health is None  # the hub never even imported the module


def test_trainer_health_off_is_inert(fresh_health, monkeypatch, toy_dataset):
    """Trainer-level guard: health_interval_s=None sends no report ever."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.runtime.parameter_server import InprocPSClient, PSClient

    calls = []
    monkeypatch.setattr(PSClient, "report_health",
                        lambda self, report: calls.append(report))
    monkeypatch.setattr(InprocPSClient, "report_health",
                        lambda self, report: calls.append(report))
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,),
                                         "num_outputs": 2},
                     input_shape=(8,))
    tr = dk.AsyncADAG(Model.init(spec, seed=0),
                      loss="categorical_crossentropy", batch_size=16,
                      num_epoch=1, num_workers=2, communication_window=4,
                      learning_rate=0.05, seed=0)
    tr.train(toy_dataset)
    assert calls == []
    assert health_mod.collector().workers() == []


def test_trainer_health_interval_validation():
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,),
                                         "num_outputs": 2},
                     input_shape=(8,))
    model = Model.init(spec, seed=0)
    with pytest.raises(ValueError, match="health_interval_s"):
        dk.AsyncADAG(model, loss="categorical_crossentropy",
                     health_interval_s=0.0)
    # native_ps + health_interval_s over sockets is served since ISSUE 11
    # (the C++ hub ingests action-M reports); no guard to pin here


def test_trainer_with_health_interval_reports_and_detects(fresh_health,
                                                          toy_dataset):
    """The live plane end to end at trainer level (no telemetry needed —
    health has its own opt-in): every worker lands at least one report,
    windows/s series materialize, and the snapshot is JSON-safe."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,),
                                         "num_outputs": 2},
                     input_shape=(8,))
    tr = dk.AsyncADAG(Model.init(spec, seed=0),
                      loss="categorical_crossentropy", batch_size=16,
                      num_epoch=2, num_workers=2, communication_window=2,
                      learning_rate=0.05, seed=0, health_interval_s=0.05)
    tr.train(toy_dataset)
    col = health_mod.collector()
    assert col.workers() == ["0", "1"]
    for w in ("0", "1"):
        assert (col.meta(w) or {}).get("reports", 0) >= 1
        assert col.series(w, "windows_total").last() > 0
        assert col.series(w, "window_wall_ms") is not None
    json.dumps(health_mod.health_snapshot())


def test_trainer_owned_hub_run_starts_with_clean_health_slate(fresh_health,
                                                              toy_dataset):
    """A second train() on a trainer-owned hub must not inherit the first
    run's series or the monitor's frozen throughput baseline: run 2's
    ramp-up would read as a throughput regression against run 1's steady
    state, and run 1's workers would skew the straggler median for the
    whole 120s window."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    # plant stale state as if a previous run just ended: a leftover
    # worker series and a frozen throughput baseline
    health_mod.collector().observe("99", "windows_total", 1e9)
    mon = health_mod.monitor()
    mon._thr_baseline = 1e9
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,),
                                         "num_outputs": 2},
                     input_shape=(8,))
    tr = dk.AsyncADAG(Model.init(spec, seed=0),
                      loss="categorical_crossentropy", batch_size=16,
                      num_epoch=1, num_workers=2, communication_window=2,
                      learning_rate=0.05, seed=0, health_interval_s=0.05)
    tr.train(toy_dataset)
    col = health_mod.collector()
    assert "99" not in col.workers(), "stale worker survived the reset"
    assert mon._thr_baseline != 1e9, "frozen baseline survived the reset"
    assert not [e for e in mon.events()
                if e.kind == "throughput_regression"], \
        "stale baseline fired a spurious regression on the fresh run"


def test_client_failover_dedup_key_is_gc_stable():
    """The failover dedup key must be a process-monotonic ordinal, not
    id(self): CPython reuses addresses after GC, and a recycled id lets a
    replacement client's failover land inside the dead client's cooldown
    and vanish from the ring/JSONL."""
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    ps = DeltaParameterServer(_weights(), port=0, idle_timeout=None)
    ps.start()
    try:
        ordinals = []
        for _ in range(3):
            # sequential create/close/GC: with id(self) keys these clients
            # routinely land on the same address and would share a key
            with PSClient("127.0.0.1", ps.port, templates=_weights()) as c:
                ordinals.append(c._client_ordinal)
        assert len(set(ordinals)) == 3
        assert ordinals == sorted(ordinals)
    finally:
        ps.stop()


# -- the acceptance drill ------------------------------------------------------

@pytest.mark.chaos
def test_live_drill_straggler_and_failover_events_visible_mid_run(
        telemetry, tmp_path):
    """ISSUE-8 acceptance, scaled to CI: real PS workers stream health
    reports while one of them is routed through a ChaosProxy that delays
    every frame and the PRIMARY hub is killed on its commit clock.
    Both events — straggler naming the delayed worker, failover naming
    the promoted standby — must be observable DURING the run through the
    punchcard ``fetch_telemetry(..., health=True)`` pull."""
    from distkeras_tpu.runtime.faults import ChaosProxy, HubKillPlan
    from distkeras_tpu.runtime.job_deployment import Punchcard, fetch_telemetry
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    t = _weights()
    primary = DeltaParameterServer(t, port=0, idle_timeout=None)
    primary.start()
    replica = DeltaParameterServer(
        t, idle_timeout=None, replica_of=("127.0.0.1", primary.port))
    replica.start()
    proxy = ChaosProxy("127.0.0.1", primary.port, delay_all_s=0.05)
    proxy.start()
    # fast detector cadence for the drill; straggler needs >= 3 reporters
    mon = health_mod.monitor()
    mon.check_interval_s = 0.05
    mon.cooldown_s = 0.0
    mon.jsonl_path = str(tmp_path / "health.jsonl")
    pc = Punchcard(secret="drill").start()
    kill_plan = HubKillPlan(after_commits=48)
    seen_mid_run = {}
    stop = threading.Event()

    def stop_proxy_with_primary():
        # the proxy models the slow network path TO the primary: once the
        # primary dies the path dies with it (a proxy that keeps accepting
        # for a dead upstream would eat the client's reconnect budget —
        # every connect "succeeds" and the rotation never advances)
        kill_plan.fired.wait(timeout=120)
        proxy.stop()

    threading.Thread(target=stop_proxy_with_primary, daemon=True).start()

    def poll():
        while not stop.is_set():
            try:
                resp = fetch_telemetry("127.0.0.1", pc.port, "drill",
                                       health=True)
            except (OSError, ValueError):
                time.sleep(0.02)
                continue
            for ev in resp["health"]["events"]:
                seen_mid_run.setdefault(ev["kind"], ev)
            time.sleep(0.02)

    worker_errors = []

    def worker(idx, port, windows):
        # a worker dying (e.g. a health report crashing the hub handler
        # and burning the reconnect budget) must FAIL the drill, not pass
        # it because the events happened to fire first
        try:
            with PSClient("127.0.0.1", port, templates=t,
                          failover=[("127.0.0.1", replica.port)],
                          max_reconnects=12, reconnect_backoff=0.02) as c:
                for w in range(windows):
                    t0 = time.perf_counter()
                    c.pull()
                    c.commit(_ones())
                    c.report_health(_report(
                        idx, seq=w, windows_total=float(w + 1),
                        window_wall_ms=(time.perf_counter() - t0) * 1e3,
                        reconnects_total=float(c.reconnects_used),
                        failovers_total=float(c.failovers_used)))
                c.drain()
        except Exception as e:
            worker_errors.append((idx, e))

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    kill_plan.start(primary)
    threads = [threading.Thread(target=worker, args=(i, primary.port, 24))
               for i in range(3)]
    delayed = threading.Thread(target=worker, args=(3, proxy.port, 24))
    threads.append(delayed)
    try:
        # the proxied worker goes first, alone, until min_samples of its
        # DELAYED walls have landed: if the fast workers raced it to the
        # kill clock, the primary could die with worker 3's big-wall
        # reports still queued in the proxy pipe — its collected series
        # would then hold mostly fast post-failover samples and the
        # straggler condition would be down to load luck
        delayed.start()
        assert _wait_until(
            lambda: (health_mod.collector().meta("3")
                     or {}).get("reports", 0) >= 3, timeout=30), \
            "proxied worker landed no delayed reports"
        for th in threads[:3]:
            th.start()
        for th in threads:
            th.join(timeout=120)
        assert not any(th.is_alive() for th in threads)
        kill_plan.join()
        assert kill_plan.fired.is_set(), "primary never killed"
        assert _wait_until(lambda: replica.promoted, timeout=10)
        # give the poller one more detector cadence to observe the tail
        _wait_until(lambda: {"straggler", "failover"} <= set(seen_mid_run),
                    timeout=10)
    finally:
        stop.set()
        poller.join(timeout=5)
        kill_plan.cancel()
        pc.stop()
        proxy.stop()
        replica.stop()
        try:
            primary.stop()
        except Exception:
            pass
    assert worker_errors == [], worker_errors
    # straggler fired DURING the run and named the proxied worker
    assert "straggler" in seen_mid_run, sorted(seen_mid_run)
    assert seen_mid_run["straggler"]["worker"] == "3"
    # failover fired and named the promoted standby's address
    assert "failover" in seen_mid_run, sorted(seen_mid_run)
    fo = seen_mid_run["failover"]["evidence"]
    # first-seen failover event is either the hub's own promotion
    # (named by its BIND host, e.g. 0.0.0.0) or a client's landing
    # (named by the connect host) — both carry the standby's port
    promoted = fo.get("promoted") or fo.get("to_addr")
    assert promoted.endswith(f":{replica.port}")
    # the durable sink carries both too
    kinds = {json.loads(line)["kind"]
             for line in (tmp_path / "health.jsonl").read_text().splitlines()}
    assert {"straggler", "failover"} <= kinds
