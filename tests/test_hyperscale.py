"""Hyperscale embedding tier (issue 15): sparse row-delta replication
(REPL_SPARSE + attach-time capability), per-table vocabularies, the
hot-tier client LRU (sparse_cache_rows), row-touch telemetry, the native
sparse direct pair, and the compat/parity matrix the issue pins."""

import time

import numpy as np
import pytest

from distkeras_tpu import observability as obs
from distkeras_tpu.runtime import networking as net
from distkeras_tpu.runtime.parameter_server import (
    ADAGParameterServer,
    DeltaParameterServer,
    InprocPSClient,
    PSClient,
    _RowLRU,
    shard_plan,
)


def _weights():
    return [np.arange(40, dtype=np.float32).reshape(10, 4),
            np.zeros((3,), np.float32)]


def _start(hub_cls=DeltaParameterServer, sparse=(0,), **kw):
    ps = hub_cls(_weights(), idle_timeout=None, sparse_leaves=sparse, **kw)
    ps.start()
    return ps


# -- wire: hello capability + REPL_SPARSE framing ------------------------------

def test_repl_hello_capability_byte():
    plain = net.encode_repl_hello(7)
    sparse = net.encode_repl_hello(7, capabilities=net.REPL_CAP_SPARSE)
    _, blobs = net.decode_tensors(plain)
    assert len(blobs[0]) == 9
    assert net.decode_repl_caps(blobs[0]) == 0
    _, blobs = net.decode_tensors(sparse)
    assert len(blobs[0]) == 10
    assert net.decode_repl_caps(blobs[0]) == net.REPL_CAP_SPARSE
    # a pre-ISSUE-15 primary slices the first 9 bytes off the 10-byte
    # hello: clock + kind decode unchanged (no torn handshake either way)
    clock, kind = net.decode_repl_header(blobs[0])
    assert (clock, kind) == (7, net.REPL_HELLO)


def _raw_standby(port, capabilities):
    """A hand-rolled standby: dial, hello, return the socket."""
    sock = net.connect("127.0.0.1", port)
    net.send_frame(sock, net.encode_repl_hello(0, capabilities=capabilities))
    return sock


def _read_repl_frames(sock, n, limit=1 << 22):
    frames = []
    for _ in range(n):
        action, blobs = net.recv_tensors(sock, limit=limit)
        assert action == net.ACTION_REPL
        clock, kind = net.decode_repl_header(bytes(memoryview(blobs[0]))[:9])
        frames.append((clock, kind, blobs))
    return frames


def _sparse_commit(port, ids, value, templates=None):
    templates = templates or _weights()
    with PSClient("127.0.0.1", port, templates=templates,
                  sparse_leaves=[0]) as c:
        c.pull()
        d = [np.zeros_like(templates[0]), np.ones((3,), np.float32)]
        d[0][ids] = value
        c.commit(d, sparse_rows=[ids])


def test_sparse_primary_frames_by_attach_time_capability():
    """The never-a-torn-stream pin: one sparse primary, two hand-rolled
    standbys — the legacy (9-byte) hello receives ONLY SYNC/DELTA frames
    for the same sparse commits that reach the capable hello as
    REPL_SPARSE row deltas."""
    ps = _start()
    try:
        legacy = _raw_standby(ps.port, 0)
        capable = _raw_standby(ps.port, net.REPL_CAP_SPARSE)
        ids = np.array([2, 7], np.int64)
        _sparse_commit(ps.port, ids, 1.5)
        legacy_frames = _read_repl_frames(legacy, 2)
        capable_frames = _read_repl_frames(capable, 2)
        assert [k for _, k, _ in legacy_frames] == [net.REPL_SYNC,
                                                    net.REPL_DELTA]
        assert [k for _, k, _ in capable_frames] == [net.REPL_SYNC,
                                                     net.REPL_SPARSE]
        # the sparse frame carries exactly (header, ids, rows, dense head)
        _, _, blobs = capable_frames[1]
        assert len(blobs) == 1 + 2 + 1
        got_ids = np.frombuffer(bytes(memoryview(blobs[1])), np.int64)
        np.testing.assert_array_equal(got_ids, ids)
        rows = np.frombuffer(bytes(memoryview(blobs[2])),
                             np.float32).reshape(2, 4)
        np.testing.assert_array_equal(rows, np.full((2, 4), 1.5))
        # and it is strictly smaller than the dense-R frame next to it
        dense_size = sum(len(bytes(memoryview(b)))
                         for b in legacy_frames[1][2])
        sparse_size = sum(len(bytes(memoryview(b))) for b in blobs)
        assert sparse_size < dense_size
        legacy.close()
        capable.close()
    finally:
        ps.stop()


def test_sparse_and_dense_standbys_track_bit_identical():
    """The replication parity pin: a sparse-capable standby (row-delta
    stream) and a legacy standby (dense-R fallback) applied the SAME
    commit sequence land bit-identical to the primary and to each
    other — f32 and int8 commits, dense and sparse."""
    prim = _start()
    sb_sparse = DeltaParameterServer(_weights(), idle_timeout=None,
                                     sparse_leaves=[0],
                                     replica_of=("127.0.0.1", prim.port))
    sb_sparse.start()
    sb_dense = DeltaParameterServer(_weights(), idle_timeout=None,
                                    replica_of=("127.0.0.1", prim.port))
    sb_dense.start()
    try:
        assert sb_sparse.wait_synced(10)
        assert sb_dense.wait_synced(10)
        with PSClient("127.0.0.1", prim.port, templates=_weights(),
                      sparse_leaves=[0]) as c, \
                PSClient("127.0.0.1", prim.port, templates=_weights(),
                         sparse_leaves=[0], compress="int8") as q:
            for cl, val in ((c, 0.37), (q, -0.21)):
                cl.pull()
                d = [np.zeros((10, 4), np.float32),
                     np.full((3,), 0.11, np.float32)]
                d[0][np.array([1, 4, 8])] = val
                cl.commit(d, sparse_rows=[np.array([1, 4, 8], np.int64)])
            # one DENSE commit interleaves too (full-delta control client)
            with PSClient("127.0.0.1", prim.port,
                          templates=_weights()) as dense_client:
                dense_client.pull()
                dense_client.commit([np.full((10, 4), 0.05, np.float32),
                                     np.zeros((3,), np.float32)])
        deadline = time.time() + 10
        while time.time() < deadline and (
                sb_sparse.num_updates < 3 or sb_dense.num_updates < 3):
            time.sleep(0.02)
        pw = prim.get_weights()
        for sb in (sb_sparse, sb_dense):
            for a, b in zip(pw, sb.get_weights()):
                np.testing.assert_array_equal(a, b)
        assert prim._feed.repl_sparse_bytes > 0
    finally:
        sb_sparse.stop()
        sb_dense.stop()
        prim.stop()


def test_adaptive_merged_sparse_batch_replicates_row_union():
    """An adaptive sparse primary publishes the merged batch sparse; a
    sparse standby tracks it bit for bit."""
    prim = _start(adaptive=True)
    sb = DeltaParameterServer(_weights(), idle_timeout=None,
                              sparse_leaves=[0],
                              replica_of=("127.0.0.1", prim.port))
    sb.start()
    try:
        assert sb.wait_synced(10)
        _sparse_commit(prim.port, np.array([0, 3], np.int64), 0.5)
        _sparse_commit(prim.port, np.array([3, 9], np.int64), -0.25)
        deadline = time.time() + 10
        while time.time() < deadline and sb.num_updates < 2:
            time.sleep(0.02)
        for a, b in zip(prim.get_weights(), sb.get_weights()):
            np.testing.assert_array_equal(a, b)
        assert prim._feed.repl_sparse_bytes > 0
    finally:
        sb.stop()
        prim.stop()


# -- hot-tier client LRU -------------------------------------------------------

def test_row_lru_eviction_order_and_flush():
    lru = _RowLRU(2, 3, residual=True)
    assert lru.insert(np.array([1, 2]), np.ones((2, 3), np.float32)) == []
    # touch row 1 so row 2 becomes the LRU victim
    out = np.empty((1, 3), np.float32)
    mp, miss = lru.gather(np.array([1]), out)
    assert mp.size == 0 and lru.hits == 1
    lru.store_residuals(np.array([2]), np.full((1, 3), 0.125, np.float32))
    flushed = lru.insert(np.array([5]), np.zeros((1, 3), np.float32))
    assert [rid for rid, _ in flushed] == [2]
    np.testing.assert_array_equal(flushed[0][1], np.full(3, 0.125))
    assert lru.evictions == 1
    assert sorted(lru.slots) == [1, 5]
    # merge folds only resident rows
    lru.merge(np.array([1, 2]), np.full((2, 3), 2.0, np.float32))
    out = np.empty((1, 3), np.float32)
    lru.gather(np.array([1]), out)
    np.testing.assert_array_equal(out[0], np.full(3, 3.0))


def test_cache_knob_validation():
    t = _weights()
    with pytest.raises(ValueError, match="sparse_leaves"):
        PSClient("127.0.0.1", 1, templates=t, sparse_cache_rows=4)
    with pytest.raises(ValueError, match=">= 1"):
        InprocPSClient(object(), t, sparse_leaves=[0], sparse_cache_rows=0)
    from distkeras_tpu.runtime.parameter_server import ShardedPSClient

    plan = shard_plan(t, 1, sparse_leaves=[0])
    with pytest.raises(ValueError, match="sharded"):
        ShardedPSClient([("127.0.0.1", 1)], t, plan, sparse_leaves=[0],
                        sparse_cache_rows=4)


def test_hot_tier_pull_moves_only_misses():
    """A hit row costs zero wire: the S request of a warm pull carries
    only the ids not resident in the LRU, and the result block still
    carries fresh-or-cached values for every requested id."""
    ps = _start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      sparse_leaves=[0], sparse_cache_rows=4) as c:
            c.pull()  # seeds rows [0, 4)
            sent = []
            orig = c._sp_enc.send

            def spy(sock, action, arrays):
                sent.append([np.array(a) for a in arrays])
                return orig(sock, action, arrays)

            c._sp_enc.send = spy
            ids = np.array([1, 2, 7], np.int64)
            c.pull_nowait(sparse_rows=[ids])
            block = c.wait_weights()[0]
            np.testing.assert_array_equal(
                sent[0][0], np.array([7], np.int64))  # misses only
            center = ps.get_weights()[0]
            np.testing.assert_array_equal(block, center[ids])
            assert c.sparse_cache_hits == 2
            assert c.sparse_cache_misses == 1
    finally:
        ps.stop()


def test_hot_tier_own_commits_merge_in_place():
    """Hits merge in place: after this client commits a delta for a
    resident row, a warm (zero-wire) pull of that row reads the updated
    value — exact under a scale-1 hub."""
    ps = _start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      sparse_leaves=[0], sparse_cache_rows=4) as c:
            c.pull()
            ids = np.array([1], np.int64)
            c.pull_nowait(sparse_rows=[ids])
            before = c.wait_weights()[0].copy()
            d = [np.zeros((10, 4), np.float32), np.zeros((3,), np.float32)]
            d[0][1] = 2.25
            c.commit(d, sparse_rows=[ids])
            c.pull_nowait(sparse_rows=[ids])
            after = c.wait_weights()[0]
            np.testing.assert_array_equal(after, before + 2.25)
            np.testing.assert_array_equal(after, ps.get_weights()[0][ids])
    finally:
        ps.stop()


def test_evict_forces_flush_conserves_int8_residuals():
    """A tiny cache under int8: evicted rows' pending residuals ride the
    next commit (ids union), so the hub's center tracks the true delta
    sum within quantization tolerance — eviction never LOSES residuals."""
    ps = _start()
    try:
        true_sum = np.zeros((10, 4), np.float32)
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      sparse_leaves=[0], sparse_cache_rows=2,
                      compress="int8") as c:
            c.pull()
            rng = np.random.default_rng(0)
            for start in (0, 3, 6, 1, 4):
                ids = np.arange(start, start + 3, dtype=np.int64)
                c.pull_nowait(sparse_rows=[ids])
                c.wait_weights()
                d = [np.zeros((10, 4), np.float32),
                     np.zeros((3,), np.float32)]
                d[0][ids] = rng.normal(size=(3, 4)).astype(np.float32)
                true_sum += d[0]
                c.commit(d, sparse_rows=[ids])
            assert sum(l.evictions for l in c._lru.values()) > 0
        w0 = _weights()[0]
        got = ps.get_weights()[0] - w0
        # block-quantized int8 error feedback: each row's final pending
        # residual is bounded by one quantization step of its last block
        assert np.max(np.abs(got - true_sum)) < 0.1
    finally:
        ps.stop()


# -- per-table vocabularies ----------------------------------------------------

def test_multi_table_plan_reduces_to_single_table_plan():
    """The reduction pin: when every vocabulary matches, the multi-table
    row-range plan is exactly today's single-table plan per leaf."""
    t_multi = [np.zeros((12, 4), np.float32), np.zeros((12, 4), np.float32),
               np.zeros((5,), np.float32)]
    plan = shard_plan(t_multi, 3, sparse_leaves=[0, 1])
    single = shard_plan([t_multi[0], t_multi[2]], 3, sparse_leaves=[0])
    assert plan.sparse_ranges[0] == plan.sparse_ranges[1] \
        == single.sparse_ranges[0]
    # and mismatched vocabularies get INDEPENDENT per-leaf ranges
    t_mixed = [np.zeros((12, 4), np.float32), np.zeros((30, 4), np.float32)]
    p2 = shard_plan(t_mixed, 3, sparse_leaves=[0, 1])
    assert p2.sparse_ranges[0] == ((0, 4), (4, 8), (8, 12))
    assert p2.sparse_ranges[1] == ((0, 10), (10, 20), (20, 30))


def test_sparse_table_fields_resolution():
    from distkeras_tpu.models.base import (Model, sparse_leaf_indices,
                                           sparse_table_fields)
    from distkeras_tpu.models.embedding import ctr_embedding_spec

    spec = ctr_embedding_spec([16, 24, 8], dim=4)
    model = Model.init(spec, seed=0)
    idx = sparse_leaf_indices(spec, model.params)
    assert len(idx) == 3
    fields = sparse_table_fields(spec, model.params)
    assert fields == ((0,), (1,), (2,))
    # the single-table architecture declares no map (shared contract)
    spec1 = ctr_embedding_spec(16, dim=4, fields=2)
    m1 = Model.init(spec1, seed=0)
    assert sparse_table_fields(spec1, m1.params) is None


def test_multi_vocab_ids_validate_per_table():
    """Per-table validation: an id legal in the large vocabulary is
    rejected for the small one (the shared-id contract would have sent
    it everywhere)."""
    t = [np.zeros((4, 2), np.float32), np.zeros((16, 2), np.float32)]
    ps = DeltaParameterServer(t, idle_timeout=None, sparse_leaves=[0, 1])
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=t,
                      sparse_leaves=[0, 1]) as c:
            with pytest.raises(ValueError):
                c.pull_nowait(sparse_rows=[np.array([9]), np.array([9])])
            c.pull_nowait(sparse_rows=[np.array([2]), np.array([9])])
            out = c.wait_weights()
            assert out[0].shape[0] == 4  # full cache handed out
    finally:
        ps.stop()


def test_multi_vocab_trainer_end_to_end():
    """Tiny multi-table CTR run: per-field vocabularies of different
    sizes train over per-table id sets (auto-resolved field map)."""
    from distkeras_tpu.data.ctr import synthetic_ctr_dataset
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.embedding import ctr_embedding_spec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG

    spec = ctr_embedding_spec([24, 48], dim=4, hidden_sizes=(8,))
    ds = synthetic_ctr_dataset(64, [24, 48], seed=0, hot_prob=0.5)
    tr = AsyncADAG(Model.init(spec, seed=0),
                   loss="categorical_crossentropy", batch_size=8,
                   num_epoch=1, learning_rate=0.05, seed=0, num_workers=2,
                   communication_window=2, sparse_tables="auto")
    model = tr.train(ds, shuffle=False)
    assert len(tr.history) == 4
    assert all(np.isfinite(h) for h in tr.history)
    import jax

    shapes = sorted(np.asarray(l).shape for l in jax.tree.leaves(model.params)
                    if getattr(l, "ndim", 0) == 2 and l.shape[-1] == 4)
    assert (24, 4) in shapes and (48, 4) in shapes


# -- trainer parity pins (LRU vs full cache) -----------------------------------

def _ctr_run(cache, compress=None, transport="socket", native=False):
    from distkeras_tpu.data.ctr import synthetic_ctr_dataset
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.embedding import ctr_embedding_spec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG

    spec = ctr_embedding_spec(64, dim=4, fields=2, hidden_sizes=(8,))
    ds = synthetic_ctr_dataset(96, 64, fields=2, seed=0, hot_prob=0.0)
    tr = AsyncADAG(Model.init(spec, seed=0),
                   loss="categorical_crossentropy", batch_size=8,
                   num_epoch=2, learning_rate=0.05, seed=0, num_workers=1,
                   communication_window=2, transport=transport,
                   native_ps=native, sparse_tables="auto",
                   sparse_cache_rows=cache, compress_commits=compress)
    return tr.train(ds, shuffle=False)


def _assert_params_equal(a, b):
    import jax

    for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("compress", [None, "int8"])
def test_lru_cache_trajectory_identical_to_full_cache(compress):
    """The issue-15 parity pin: cache_rows >= vocabulary makes the
    hot-tier client trajectory-identical to the PR-9 full cache, f32 AND
    int8 (no evictions -> identical wire bytes, identical merges)."""
    _assert_params_equal(_ctr_run(None, compress), _ctr_run(64, compress))


@pytest.mark.slow
@pytest.mark.parametrize("transport,native", [("inproc", False),
                                              ("inproc", True)])
def test_lru_cache_parity_other_transports(transport, native):
    ref = _ctr_run(None, None, "socket", False)
    got = _ctr_run(64, None, transport, native)
    _assert_params_equal(ref, got)


def test_native_inproc_sparse_matches_python_hub():
    """The formerly-NotImplementedError cell (sparse + inproc + native)
    is bit-identical to the Python hub."""
    _assert_params_equal(_ctr_run(None, None, "inproc", False),
                         _ctr_run(None, None, "inproc", True))


def test_replicated_sparse_trainer_standby_tracks_center():
    """E2E: a sparse-capable standby attached to the trainer-owned
    primary ends the run holding the primary's final center bit for bit
    (row-delta replication behind the ack)."""
    from distkeras_tpu.data.ctr import synthetic_ctr_dataset
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.embedding import ctr_embedding_spec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG
    from distkeras_tpu.utils import flatten_weights

    spec = ctr_embedding_spec(64, dim=4, fields=2, hidden_sizes=(8,))
    ds = synthetic_ctr_dataset(64, 64, fields=2, seed=0, hot_prob=0.0)
    model = Model.init(spec, seed=0)
    flat, _ = flatten_weights(model.params)
    flat = [np.asarray(w, np.float32) for w in flat]
    from distkeras_tpu.models.base import sparse_leaf_indices

    sparse_idx = sparse_leaf_indices(spec, model.params)
    hub = ADAGParameterServer(flat, num_workers=1, idle_timeout=None,
                              sparse_leaves=sparse_idx)
    hub.start()
    sb = ADAGParameterServer(flat, num_workers=1, idle_timeout=None,
                             sparse_leaves=sparse_idx,
                             replica_of=("127.0.0.1", hub.port))
    sb.start()
    try:
        assert sb.wait_synced(10)
        tr = AsyncADAG(model, loss="categorical_crossentropy", batch_size=8,
                       num_epoch=1, learning_rate=0.05, seed=0,
                       num_workers=1, communication_window=2,
                       sparse_tables="auto",
                       ps_address=("127.0.0.1", hub.port))
        tr.train(ds, shuffle=False)
        deadline = time.time() + 10
        while time.time() < deadline and sb.num_updates < hub.num_updates:
            time.sleep(0.02)
        for a, b in zip(hub.get_weights(), sb.get_weights()):
            np.testing.assert_array_equal(a, b)
        assert hub._feed.repl_sparse_bytes > 0
    finally:
        sb.stop()
        hub.stop()


# -- row-touch telemetry -------------------------------------------------------

def test_hub_hot_set_estimate_and_cache_counters():
    obs.enable()
    obs.reset()
    try:
        ps = _start()
        # 4 windows x (1 pull + 1 commit) = 8 folds -> exactly one decay
        # tick publishes the gauge with rows 1/2 at touch 4 -> 2 (the
        # pulls carry ZERO ids wire-side — they are warm hits)
        ps.TOUCH_DECAY_EVERY = 8
        try:
            with PSClient("127.0.0.1", ps.port, templates=_weights(),
                          sparse_leaves=[0], sparse_cache_rows=3) as c:
                c.pull()
                for _ in range(4):
                    ids = np.array([1, 2], np.int64)
                    c.pull_nowait(sparse_rows=[ids])
                    c.wait_weights()
                    d = [np.zeros((10, 4), np.float32),
                         np.zeros((3,), np.float32)]
                    d[0][ids] = 0.1
                    c.commit(d, sparse_rows=[ids])
                snap = obs.snapshot()
                gauges = dict(snap["gauges"])
                hot = [v for k, v in gauges.items()
                       if k.startswith("ps.sparse_hot_rows")]
                assert hot and hot[0] >= 2
                counters = dict(snap["counters"])
                hits = sum(v for k, v in counters.items()
                           if k.startswith("ps_sparse_cache_hits_total"))
                assert hits > 0
                assert c.sparse_cache_hits + c.sparse_cache_misses > 0
        finally:
            ps.stop()
    finally:
        obs.disable()
        obs.reset()


def test_repl_sparse_bytes_saved_counter():
    obs.enable()
    obs.reset()
    try:
        prim = _start()
        sb = DeltaParameterServer(_weights(), idle_timeout=None,
                                  sparse_leaves=[0],
                                  replica_of=("127.0.0.1", prim.port))
        sb.start()
        try:
            assert sb.wait_synced(10)
            _sparse_commit(prim.port, np.array([3], np.int64), 0.5)
            counters = dict(obs.snapshot()["counters"])
            saved = sum(v for k, v in counters.items()
                        if k.startswith("ps.repl_sparse_bytes_saved"))
            assert saved > 0
        finally:
            sb.stop()
            prim.stop()
    finally:
        obs.disable()
        obs.reset()


def test_render_top_hit_and_repl_columns():
    from distkeras_tpu.observability.health import render_top

    frame = render_top({"fleet": {"workers": {
        "0": {"meta": {"shard": None, "age_s": 1.0},
              "metrics": {
                  "sparse_cache_hits_total": {"last": 30.0, "n": 2},
                  "sparse_cache_misses_total": {"last": 10.0, "n": 2}}},
        "hub": {"meta": {"age_s": 1.0},
                "metrics": {"repl_sparse_bytes_total":
                            {"last": 4096.0, "rate": 512.0, "n": 3}}},
    }}, "events": []})
    assert "HIT%" in frame and "RΔ/S" in frame
    row0 = next(ln for ln in frame.splitlines() if ln.lstrip().startswith("0"))
    assert "75.0" in row0
    hub_row = next(ln for ln in frame.splitlines()
                   if ln.lstrip().startswith("hub"))
    assert "512" in hub_row


def test_fleet_report_hot_tier_block():
    from distkeras_tpu.observability.distributed import _hot_tier_block

    snap = {"workers": {
        "0": {"metrics": {
            "sparse_cache_hits_total": {"last": 9.0, "n": 1},
            "sparse_cache_misses_total": {"last": 3.0, "n": 1}}},
        "hub": {"metrics": {
            "repl_sparse_bytes_total": {"last": 2048.0, "n": 1}}},
    }}
    block = _hot_tier_block(snap)
    assert block["cache"]["0"]["hit_rate"] == 0.75
    assert block["repl_sparse_bytes_total"] == 2048
    assert _hot_tier_block({"workers": {}}) is None


# -- un-upgraded peers ---------------------------------------------------------

def test_plain_replicated_stream_stays_repl_sparse_free():
    """Compat: a hub with NO sparse leaves never emits a REPL_SPARSE
    frame, even to a capability-announcing standby (there is nothing
    sparse to frame) — the dense replicated byte stream is untouched."""
    t = _weights()
    prim = DeltaParameterServer(t, idle_timeout=None)
    prim.start()
    try:
        sock = _raw_standby(prim.port, net.REPL_CAP_SPARSE)
        with PSClient("127.0.0.1", prim.port, templates=t) as c:
            c.pull()
            c.commit([np.full_like(a, 0.25) for a in t])
        frames = _read_repl_frames(sock, 2)
        assert [k for _, k, _ in frames] == [net.REPL_SYNC, net.REPL_DELTA]
        sock.close()
    finally:
        prim.stop()
