"""Punchcard job-deployment round trips (reference: distkeras/job_deployment.py).

The reference layer was submit-a-job-with-a-secret to a service on the
cluster head and get a trained model back (SURVEY.md §2.18).  These tests
run the daemon in-process on localhost and drive the full client surface:
submit/status/wait/fetch/run, inline and npz-path datasets, auth failure,
queue FIFO, and path-traversal containment.
"""

import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.base import ModelSpec
from distkeras_tpu.runtime.job_deployment import (
    DONE, FAILED, Job, Punchcard, list_jobs, shutdown)

SECRET = "test-secret"


def _toy_data(n=256, dim=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=3.0, size=(classes, dim))
    labels = rng.integers(0, classes, size=n)
    feats = centers[labels] + rng.normal(scale=0.5, size=(n, dim))
    onehot = np.eye(classes, dtype=np.float32)[labels]
    return feats.astype(np.float32), onehot, labels


def _spec(dim=8, classes=4):
    return ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": classes},
                     input_shape=(dim,))


@pytest.fixture()
def punchcard(tmp_path):
    pc = Punchcard(secret=SECRET, data_root=str(tmp_path)).start()
    yield pc
    pc.stop()


def test_submit_run_fetch_roundtrip(punchcard):
    feats, onehot, labels = _toy_data()
    ds = Dataset({"features": feats, "label": onehot})
    job = Job("127.0.0.1", punchcard.port, SECRET, name="roundtrip",
              model=_spec(), trainer="single",
              trainer_kwargs={"num_epoch": 20, "batch_size": 32,
                              "learning_rate": 0.1},
              data=ds)
    model = job.run(timeout=120)
    st = job.status()
    assert st["state"] == DONE
    assert st["training_time"] > 0
    assert len(st["history"]) > 0 and st["history"][-1] < st["history"][0]
    preds = model.predict(feats).argmax(axis=-1)
    assert (preds == labels).mean() > 0.8


def test_distributed_trainer_job(punchcard):
    """The daemon executes the flagship DISTRIBUTED trainer on a
    multi-replica CPU mesh (round-3 verdict task 6): the submitted ADAG
    config names an explicit 4-replica mesh, the job trains across it
    inside the daemon process, and the fetched center model has actually
    learned — not just produced the right shapes."""
    feats, onehot, labels = _toy_data(n=512)
    ds = Dataset({"features": feats, "label": onehot})
    job = Job("127.0.0.1", punchcard.port, SECRET, name="adag-job",
              model=_spec(), trainer="adag",
              trainer_kwargs={"num_epoch": 10, "batch_size": 16,
                              "num_workers": 4, "learning_rate": 0.1,
                              "communication_window": 2},
              data=ds)
    model = job.run(timeout=240)
    st = job.status()
    assert st["state"] == DONE
    # the daemon-side trainer really ran a multi-window distributed loop
    assert len(st["history"]) > 1 and st["history"][-1] < st["history"][0]
    preds = model.predict(feats).argmax(axis=-1)
    assert (preds == labels).mean() > 0.8, "center model did not learn"


def test_npz_path_dataset(punchcard, tmp_path):
    feats, onehot, _ = _toy_data()
    np.savez(tmp_path / "train.npz", features=feats, label=onehot)
    job = Job("127.0.0.1", punchcard.port, SECRET, name="npz-job",
              model=_spec(), trainer="single",
              trainer_kwargs={"num_epoch": 2, "batch_size": 32},
              dataset_path="train.npz")
    model = job.run(timeout=120)
    assert model.predict(feats).shape == (256, 4)


def test_wrong_secret_rejected(punchcard):
    feats, onehot, _ = _toy_data(n=64)
    job = Job("127.0.0.1", punchcard.port, "wrong-secret", name="intruder",
              model=_spec(), trainer="single",
              data=Dataset({"features": feats, "label": onehot}))
    with pytest.raises(PermissionError):
        job.submit()
    assert list_jobs("127.0.0.1", punchcard.port, SECRET) == []


def test_path_traversal_rejected(punchcard):
    job = Job("127.0.0.1", punchcard.port, SECRET, name="escape",
              model=_spec(), trainer="single",
              dataset_path="../../../etc/passwd")
    with pytest.raises(RuntimeError, match="escapes the data root"):
        job.submit()


def test_unknown_trainer_rejected(punchcard):
    feats, onehot, _ = _toy_data(n=64)
    job = Job("127.0.0.1", punchcard.port, SECRET, name="bogus",
              model=_spec(), trainer="single",
              data=Dataset({"features": feats, "label": onehot}))
    job.trainer = "spark-rdd"  # not a thing here
    with pytest.raises(RuntimeError, match="unknown trainer"):
        job.submit()


def test_unknown_job_id(punchcard):
    feats, onehot, _ = _toy_data(n=64)
    job = Job("127.0.0.1", punchcard.port, SECRET, name="ghost",
              model=_spec(), trainer="single",
              data=Dataset({"features": feats, "label": onehot}))
    job.job_id = "nonexistent"
    with pytest.raises(RuntimeError, match="unknown job_id"):
        job.status()


def test_failed_job_surfaces_error(punchcard):
    # 8 rows with batch_size 64 -> trainer raises; job must land in FAILED
    feats, onehot, _ = _toy_data(n=8)
    job = Job("127.0.0.1", punchcard.port, SECRET, name="doomed",
              model=_spec(), trainer="single",
              trainer_kwargs={"num_epoch": 1, "batch_size": 64},
              data=Dataset({"features": feats, "label": onehot}))
    job.submit()
    st = job.wait(timeout=60)
    assert st["state"] == FAILED
    assert st["error"]
    with pytest.raises(RuntimeError, match="not done"):
        job.fetch_models()


def test_fifo_queue_and_list(punchcard):
    feats, onehot, _ = _toy_data(n=128)
    ds = Dataset({"features": feats, "label": onehot})
    jobs = []
    for i in range(3):
        j = Job("127.0.0.1", punchcard.port, SECRET, name=f"q{i}",
                model=_spec(), trainer="single",
                trainer_kwargs={"num_epoch": 1, "batch_size": 32},
                data=ds)
        j.submit()
        jobs.append(j)
    for j in jobs:
        assert j.wait(timeout=120)["state"] == DONE
    listed = list_jobs("127.0.0.1", punchcard.port, SECRET)
    assert sorted(x["name"] for x in listed) == ["q0", "q1", "q2"]


def test_oversized_preauth_frame_dropped(punchcard):
    # an unauthenticated peer declaring a huge frame must be disconnected
    # without the server allocating the declared size
    import socket
    import struct

    from distkeras_tpu.runtime import networking as net

    sock = socket.create_connection(("127.0.0.1", punchcard.port), timeout=5)
    try:
        net.recv_json(sock)  # hello
        sock.sendall(struct.pack(">Q", 1 << 33))  # "16 GiB incoming"
        sock.settimeout(5)
        assert sock.recv(1) == b""  # server hung up, no reply
    finally:
        sock.close()
    # daemon still healthy afterwards
    assert list_jobs("127.0.0.1", punchcard.port, SECRET) == []


def test_wrong_secret_never_uploads_data(punchcard, monkeypatch):
    # two-phase submit: a rejected client must fail BEFORE streaming tensors
    from distkeras_tpu.runtime import job_deployment as jd

    sent = []
    real = jd.net.send_tensors
    monkeypatch.setattr(jd.net, "send_tensors",
                        lambda *a, **kw: (sent.append(1), real(*a, **kw)))
    feats, onehot, _ = _toy_data(n=64)
    job = Job("127.0.0.1", punchcard.port, "wrong-secret", name="intruder2",
              model=_spec(), trainer="single",
              data=Dataset({"features": feats, "label": onehot}))
    with pytest.raises(PermissionError):
        job.submit()
    assert sent == []


def test_remote_shutdown():
    pc = Punchcard(secret=SECRET).start()
    shutdown("127.0.0.1", pc.port, SECRET)
    # daemon stops accepting: a fresh connect must fail once sockets close
    import time

    deadline = time.time() + 10
    while time.time() < deadline:
        if not pc._running:
            break
        time.sleep(0.05)
    assert not pc._running


def test_restart_preserves_done_jobs_and_queue(tmp_path):
    """Round-2 weak #6 closed: submit -> stop daemon -> restart ->
    status/fetch of the finished job still work from the spool."""
    from distkeras_tpu.runtime.job_deployment import _Conn

    feats, onehot, _ = _toy_data()
    ds = Dataset({"features": feats, "label": onehot})

    pc = Punchcard(secret=SECRET, data_root=str(tmp_path)).start()
    try:
        done_job = Job("127.0.0.1", pc.port, SECRET, name="survives",
                       model=_spec(), trainer="single",
                       trainer_kwargs={"num_epoch": 5, "batch_size": 32,
                                       "learning_rate": 0.1},
                       data=ds)
        done_job.submit()
        done_job.wait(timeout=120)
    finally:
        pc.stop()

    pc2 = Punchcard(secret=SECRET, data_root=str(tmp_path)).start()
    try:
        with _Conn("127.0.0.1", pc2.port, SECRET) as c:
            st = c.request({"action": "status", "job_id": done_job.job_id})
        assert st["state"] == DONE
        assert st["num_models"] == 1

        done_job.port = pc2.port  # fetch the model trained BEFORE the restart
        model = done_job.fetch_models()[0]
        preds = model.predict(feats[:16])
        assert preds.shape == (16, 4)
    finally:
        pc2.stop()


def test_restart_requeues_interrupted_job(tmp_path):
    """A job spooled as RUNNING when the daemon dies is re-queued on
    restart and trains to DONE."""
    import json as _json
    import os as _os

    feats, onehot, _ = _toy_data()
    ds = Dataset({"features": feats, "label": onehot})

    pc = Punchcard(secret=SECRET, data_root=str(tmp_path)).start()
    job = Job("127.0.0.1", pc.port, SECRET, name="interrupted",
              model=_spec(), trainer="single",
              trainer_kwargs={"num_epoch": 30, "batch_size": 16,
                              "learning_rate": 0.1},
              data=ds)
    job.submit()
    pc.stop()  # may interrupt the job mid-queue or mid-run

    # doctor the spool to the RUNNING state to pin the interrupted case
    # deterministically (whatever state the stop() race reached)
    jd = _os.path.join(str(tmp_path), ".punchcard-state", "jobs", job.job_id)
    with open(_os.path.join(jd, "manifest.json")) as f:
        m = _json.load(f)
    if m["state"] != DONE:
        m["state"] = "running"
        with open(_os.path.join(jd, "manifest.json"), "w") as f:
            _json.dump(m, f)
        assert _os.path.exists(_os.path.join(jd, "data.npz"))

    pc2 = Punchcard(secret=SECRET, data_root=str(tmp_path)).start()
    try:
        job.port = pc2.port
        st = job.wait(timeout=120)
        assert st["state"] == DONE
        assert job.fetch_models()
    finally:
        pc2.stop()


def test_retention_cap_evicts_oldest(tmp_path):
    """Beyond max_retained terminal jobs the oldest records (and spool
    dirs) are evicted."""
    import os as _os

    feats, onehot, _ = _toy_data(n=64)
    ds = Dataset({"features": feats, "label": onehot})
    pc = Punchcard(secret=SECRET, data_root=str(tmp_path), max_retained=2).start()
    try:
        jobs = []
        for i in range(4):
            j = Job("127.0.0.1", pc.port, SECRET, name=f"evict-{i}",
                    model=_spec(), trainer="single",
                    trainer_kwargs={"num_epoch": 1, "batch_size": 32},
                    data=ds)
            j.submit()
            j.wait(timeout=120)
            jobs.append(j)
        listed = {j["job_id"] for j in list_jobs("127.0.0.1", pc.port, SECRET)}
        assert jobs[-1].job_id in listed and jobs[-2].job_id in listed
        assert jobs[0].job_id not in listed
        spool = _os.path.join(str(tmp_path), ".punchcard-state", "jobs")
        assert jobs[0].job_id not in set(_os.listdir(spool))
    finally:
        pc.stop()


def test_spool_not_servable_as_dataset_path(tmp_path):
    """The state spool under data_root must not be reachable through
    server-side dataset paths (other submitters' data lives there)."""
    feats, onehot, _ = _toy_data(n=64)
    ds = Dataset({"features": feats, "label": onehot})
    pc = Punchcard(secret=SECRET, data_root=str(tmp_path)).start()
    try:
        j = Job("127.0.0.1", pc.port, SECRET, name="seed", model=_spec(),
                trainer="single", trainer_kwargs={"num_epoch": 1, "batch_size": 32},
                data=ds)
        j.submit()
        j.wait(timeout=120)
        bad = Job("127.0.0.1", pc.port, SECRET, name="thief", model=_spec(),
                  trainer="single",
                  dataset_path=f".punchcard-state/jobs/{j.job_id}/data.npz")
        with pytest.raises((RuntimeError, FileNotFoundError),
                           match="state spool|not found"):
            bad.submit()
    finally:
        pc.stop()


def test_inline_column_named_file_survives_spool(tmp_path):
    """np.savez would collide a column literally named 'file' with its own
    parameter; the hand-rolled npz writer must not."""
    feats, onehot, _ = _toy_data(n=64)
    ds = Dataset({"features": feats, "label": onehot, "file": onehot[:, :1]})
    pc = Punchcard(secret=SECRET, data_root=str(tmp_path)).start()
    try:
        j = Job("127.0.0.1", pc.port, SECRET, name="filecol", model=_spec(),
                trainer="single", trainer_kwargs={"num_epoch": 1, "batch_size": 32},
                data=ds)
        j.submit()
        assert j.wait(timeout=120)["state"] == DONE
    finally:
        pc.stop()


def test_higgs_workflow_example_runs_end_to_end():
    """The ATLAS-Higgs-analogue walkthrough (SURVEY §2.21): transformers ->
    3 trainers -> predictor -> all 4 evaluators -> checkpoint-resume ->
    Punchcard deploy, top to bottom on the CPU mesh."""
    from distkeras_tpu.examples.higgs_workflow import main

    main(["--rows", "1536", "--epochs", "4", "--workers", "4"])


def test_spool_lock_rejects_second_daemon_same_state_dir(tmp_path):
    """Two daemons must not share a spool even on different ports; stale
    locks from a dead holder are taken over."""
    import os as _os

    pc = Punchcard(secret=SECRET, data_root=str(tmp_path)).start()
    try:
        with pytest.raises(RuntimeError, match="owned by a live"):
            Punchcard(secret=SECRET, data_root=str(tmp_path)).start()
    finally:
        pc.stop()
    # stale lock (fake dead pid) is taken over transparently
    lock = _os.path.join(str(tmp_path), ".punchcard-state", "daemon.lock")
    with open(lock, "w") as f:
        f.write("999999999")
    pc2 = Punchcard(secret=SECRET, data_root=str(tmp_path)).start()
    pc2.stop()
