"""dp×sp sequence-parallel LM step: must match the single-device step."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distkeras_tpu.models.base import Model
from distkeras_tpu.models.transformer import small_lm_spec
from distkeras_tpu.ops.losses import lm_token_cross_entropy
from distkeras_tpu.parallel.lm import lm_data_shardings, make_lm_train_step, shift_targets
from distkeras_tpu.parallel.mesh import create_nd_mesh


def _specs(seq_axis):
    return small_lm_spec(vocab_size=64, model_dim=32, num_heads=2, num_layers=2,
                         max_seq_len=32, seq_axis=seq_axis)


def test_dp_sp_step_matches_single_device():
    mesh = create_nd_mesh((2, 4), ("dp", "sp"))
    spec_sharded = _specs("sp")
    spec_dense = _specs(None)
    model = Model.init(spec_dense, seed=0)
    opt = optax.sgd(0.1)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, size=(4, 32)).astype(np.int32)
    targets = shift_targets(tokens)

    # single-device reference step — same fused unembed+CE the parallel
    # step uses (the test isolates the SCHEDULE, not the loss arithmetic)
    module = spec_dense.build()

    def loss_fn(params, tok, tgt):
        ce = lm_token_cross_entropy(module, params, tok, tgt)
        # the final position's target is shift padding, not a real token
        return ce[:, :-1].mean()

    loss_ref, grads = jax.value_and_grad(loss_fn)(model.params, tokens, targets)
    updates, _ = opt.update(grads, opt.init(model.params), model.params)
    params_ref = optax.apply_updates(model.params, updates)

    # sharded step on the 2x4 mesh
    step = make_lm_train_step(spec_sharded, opt, mesh)
    sharding = lm_data_shardings(mesh)
    params = jax.tree.map(jnp.array, model.params)
    params, _, loss = step(params, opt.init(params),
                           jax.device_put(tokens, sharding), jax.device_put(targets, sharding))

    # rtol/atol cover bfloat16 accumulation-order differences between the
    # ring schedule (per-block flash kernels in bf16, f32 merge) and dense
    # attention — the round-3 flash-backed ring measures ~1.5e-4 on loss
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=5e-4)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_tp_step_matches_single_device():
    """Megatron tensor parallelism over 4 ranks == the unsharded step."""
    import jax.numpy as jnp

    from distkeras_tpu.parallel.lm import lm_state_shardings

    mesh = create_nd_mesh((2, 4), ("dp", "tp"))
    spec = small_lm_spec(vocab_size=64, model_dim=32, num_heads=4, num_layers=2,
                         max_seq_len=32, seq_axis=None)
    model = Model.init(spec, seed=0)
    opt = optax.sgd(0.1)

    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 64, size=(4, 32)).astype(np.int32)
    targets = shift_targets(tokens)

    module = spec.build()

    def loss_fn(params, tok, tgt):
        ce = lm_token_cross_entropy(module, params, tok, tgt)
        return ce[:, :-1].mean()

    loss_ref, grads = jax.value_and_grad(loss_fn)(model.params, tokens, targets)
    updates, _ = opt.update(grads, opt.init(model.params), model.params)
    params_ref = optax.apply_updates(model.params, updates)

    step = make_lm_train_step(spec, opt, mesh, sp_axis=None, tp_axis="tp")
    psh, osh = lm_state_shardings(mesh, opt, model.params, tp_axis="tp")
    params = jax.device_put(jax.tree.map(jnp.array, model.params), psh)
    opt_state = jax.device_put(opt.init(params), osh)
    sharding = lm_data_shardings(mesh, sp_axis=None)
    params, _, loss = step(params, opt_state,
                           jax.device_put(tokens, sharding), jax.device_put(targets, sharding))

    # rtol covers bfloat16 accumulation-order differences: the TP split sums
    # head/FFN partial products in a different order than the dense matmul
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-3)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_dp_sp_tp_3d_step_runs_and_learns():
    """Full 3-D mesh: data x sequence x tensor parallelism in one program."""
    import jax.numpy as jnp

    from distkeras_tpu.parallel.lm import lm_state_shardings

    mesh = create_nd_mesh((2, 2, 2), ("dp", "sp", "tp"))
    spec = small_lm_spec(vocab_size=32, model_dim=32, num_heads=2, num_layers=2,
                         max_seq_len=32, seq_axis="sp")
    model = Model.init(spec, seed=3)
    opt = optax.adam(1e-2)
    step = make_lm_train_step(spec, opt, mesh, tp_axis="tp")
    psh, osh = lm_state_shardings(mesh, opt, model.params, tp_axis="tp")
    params = jax.device_put(jax.tree.map(jnp.array, model.params), psh)
    opt_state = jax.device_put(opt.init(params), osh)
    sharding = lm_data_shardings(mesh)

    rng = np.random.default_rng(4)
    tokens = rng.integers(0, 8, size=(4, 32)).astype(np.int32)
    targets = shift_targets(tokens)
    tok_d, tgt_d = jax.device_put(tokens, sharding), jax.device_put(targets, sharding)

    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, tok_d, tgt_d)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_lm_step_loss_decreases():
    mesh = create_nd_mesh((2, 4), ("dp", "sp"))
    spec = _specs("sp")
    model = Model.init(spec, seed=1)
    opt = optax.adam(1e-2)
    step = make_lm_train_step(spec, opt, mesh)
    sharding = lm_data_shardings(mesh)

    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 8, size=(8, 32)).astype(np.int32)  # low-entropy vocab
    targets = shift_targets(tokens)
    tok_d, tgt_d = jax.device_put(tokens, sharding), jax.device_put(targets, sharding)

    params = jax.tree.map(jnp.array, model.params)
    opt_state = opt.init(params)
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, tok_d, tgt_d)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
