"""Dataset loaders + chunked data plane."""

import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.loaders import load_cifar10, load_cifar100, load_mnist


def test_mnist_synthetic_fallback_shapes():
    train, test, info = load_mnist()
    assert info["synthetic"] is True  # offline environment
    assert train["features"].shape == (60000, 28, 28, 1)
    assert train["features"].dtype == np.float32
    assert 0.0 <= train["features"].min() and train["features"].max() <= 1.0
    assert train["label"].shape == (60000, 10)
    assert test["label_index"].shape == (10000,)


def test_mnist_flatten():
    train, _, _ = load_mnist(flatten=True)
    assert train["features"].shape == (60000, 784)


@pytest.mark.slow  # tier-1 budget fix (PR 11): heaviest cells ride the full suite
def test_cifar_shapes():
    train, test, info = load_cifar10()
    assert train["features"].shape == (50000, 32, 32, 3)
    train100, _, info100 = load_cifar100()
    assert train100["label"].shape == (50000, 100)


def test_synthetic_is_deterministic_and_learnable():
    a, _, _ = load_mnist()
    b, _, _ = load_mnist()
    np.testing.assert_array_equal(a["features"][:16], b["features"][:16])
    # nearest-class-mean separability on the TRAINING means: must clearly
    # beat chance (the signal is real) but stay well below ceiling (the
    # round-3 hardening intentionally makes one-shot separation impossible
    # so wall-to-target measures training, not compile time)
    x = a["features"][:4000].reshape(4000, -1)
    y = a["label_index"][:4000]
    centers = np.stack([x[y == c].mean(axis=0) for c in range(10)])
    pred = np.argmin(((x[:, None, :] - centers[None]) ** 2).sum(-1), axis=1)
    acc = (pred == y).mean()
    assert 0.2 < acc < 0.995, acc


def test_real_npz_cache_wins(tmp_path):
    x_train = np.zeros((32, 28, 28), np.uint8)
    y_train = np.arange(32) % 10
    np.savez(tmp_path / "mnist.npz", x_train=x_train, y_train=y_train,
             x_test=x_train[:8], y_test=y_train[:8])
    train, test, info = load_mnist(cache_dir=str(tmp_path))
    assert info["synthetic"] is False
    assert train["features"].shape == (32, 28, 28, 1)
    assert len(test) == 8


def test_no_fallback_raises():
    with pytest.raises(FileNotFoundError):
        load_mnist(cache_dir="/nonexistent", synthetic_fallback=False)


# -- chunked epoch -------------------------------------------------------------

def _ds(n=100):
    return Dataset({"features": np.arange(n * 3, dtype=np.float32).reshape(n, 3),
                    "label": np.arange(n, dtype=np.int32)})


def test_chunked_epoch_covers_same_rows_as_stacked():
    ds = _ds(100)
    stacked = ds.stacked_epoch(4, ["features", "label"], window=2)
    chunks = list(ds.chunked_epoch(4, ["features", "label"], window=2, chunk_windows=5))
    assert len(chunks) == 3  # 12 windows -> 5 + 5 + 2
    assert [c["features"].shape[0] for c in chunks] == [5, 5, 2]
    rejoined = np.concatenate([c["features"] for c in chunks])
    np.testing.assert_array_equal(rejoined, stacked["features"])


def test_chunked_epoch_default_is_one_chunk():
    ds = _ds(64)
    chunks = list(ds.chunked_epoch(8, ["features"], window=1))
    assert len(chunks) == 1
    assert chunks[0]["features"].shape == (8, 1, 8, 3)


def test_chunked_epoch_chunks_are_views():
    ds = _ds(64)
    (chunk,) = ds.chunked_epoch(8, ["features"], window=1, chunk_windows=8)
    assert chunk["features"].base is not None  # zero-copy reshape of a slice


def test_chunked_training_matches_unchunked():
    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.trainers import ADAG, SingleTrainer

    rng = np.random.default_rng(0)
    n = 256
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(int)
    ds = Dataset({"features": x, "label": np.eye(2, dtype=np.float32)[y]})
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))

    def run(cls, chunk_windows, **kw):
        t = cls(spec, loss="categorical_crossentropy", worker_optimizer="sgd",
                learning_rate=0.05, batch_size=8, num_epoch=2, seed=0,
                chunk_windows=chunk_windows, **kw)
        m = t.train(ds)
        return t, m

    for cls, kw in ((SingleTrainer, {}), (ADAG, {"communication_window": 2, "num_workers": 2})):
        t_full, m_full = run(cls, None, **kw)
        t_chunk, m_chunk = run(cls, 3, **kw)
        assert t_full.history == pytest.approx(t_chunk.history, rel=1e-5)
        for a, b in zip(np.asarray(list(m_full.params.values())[0]["kernel"]).ravel(),
                        np.asarray(list(m_chunk.params.values())[0]["kernel"]).ravel()):
            assert a == pytest.approx(b, rel=1e-5)


def test_raw_idx_mnist_files_load(tmp_path):
    """The four raw (gzipped) IDX files work as dropped in — no npz
    conversion step."""
    import gzip
    import struct

    rng = np.random.default_rng(0)

    def write_idx(name, arr):
        arr = np.asarray(arr, np.uint8)
        magic = 0x0800 | arr.ndim
        payload = struct.pack(">I", magic) + b"".join(
            struct.pack(">I", d) for d in arr.shape) + arr.tobytes()
        with gzip.open(tmp_path / (name + ".gz"), "wb") as f:
            f.write(payload)

    write_idx("train-images-idx3-ubyte", rng.integers(0, 256, (32, 28, 28)))
    write_idx("train-labels-idx1-ubyte", rng.integers(0, 10, (32,)))
    write_idx("t10k-images-idx3-ubyte", rng.integers(0, 256, (8, 28, 28)))
    write_idx("t10k-labels-idx1-ubyte", rng.integers(0, 10, (8,)))

    train, test, info = load_mnist(cache_dir=str(tmp_path), synthetic_fallback=False)
    assert not info["synthetic"]
    assert train["features"].shape == (32, 28, 28, 1)
    assert test["features"].shape == (8, 28, 28, 1)
    assert train["label"].shape == (32, 10)


def test_raw_cifar_pickle_batches_load(tmp_path):
    """The upstream pickled cifar-10-batches-py directory works as
    extracted — no conversion step."""
    import pickle

    rng = np.random.default_rng(1)
    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()
    for i in range(1, 6):
        batch = {b"data": rng.integers(0, 256, (4, 3072), dtype=np.uint8),
                 b"labels": rng.integers(0, 10, 4).tolist()}
        (d / f"data_batch_{i}").write_bytes(pickle.dumps(batch))
    test_batch = {b"data": rng.integers(0, 256, (6, 3072), dtype=np.uint8),
                  b"labels": rng.integers(0, 10, 6).tolist()}
    (d / "test_batch").write_bytes(pickle.dumps(test_batch))

    from distkeras_tpu.data.loaders import load_cifar10

    train, test, info = load_cifar10(cache_dir=str(tmp_path), synthetic_fallback=False)
    assert not info["synthetic"]
    assert train["features"].shape == (20, 32, 32, 3)
    assert test["features"].shape == (6, 32, 32, 3)


def test_raw_cifar_targz_loads(tmp_path):
    """The literal downloaded cifar-100-python.tar.gz works unextracted."""
    import io
    import pickle
    import tarfile

    rng = np.random.default_rng(2)

    def member(labels_key, n):
        return pickle.dumps({b"data": rng.integers(0, 256, (n, 3072), dtype=np.uint8),
                             labels_key: rng.integers(0, 100, n).tolist()})

    tar_path = tmp_path / "cifar-100-python.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tf:
        for name, blob in (("train", member(b"fine_labels", 10)),
                           ("test", member(b"fine_labels", 4))):
            ti = tarfile.TarInfo(f"cifar-100-python/{name}")
            ti.size = len(blob)
            tf.addfile(ti, io.BytesIO(blob))

    from distkeras_tpu.data.loaders import load_cifar100

    train, test, info = load_cifar100(cache_dir=str(tmp_path), synthetic_fallback=False)
    assert not info["synthetic"]
    assert train["features"].shape == (10, 32, 32, 3)
    assert test["features"].shape == (4, 32, 32, 3)


def test_synthetic_has_label_noise_and_overlap(tmp_path, monkeypatch):
    """The stand-ins must be HARD: train labels carry noise (test clean),
    and per-pixel class signal is small against the pixel noise, so
    targets take real training instead of measuring compile time."""
    # isolate from the machine's real caches (~/.keras etc.): a dev box
    # with a cached mnist.npz must not turn this into a real-data test
    monkeypatch.setenv("HOME", str(tmp_path))
    monkeypatch.delenv("DKT_DATA_DIR", raising=False)
    monkeypatch.chdir(tmp_path)
    train, test, info = load_mnist(cache_dir=str(tmp_path))
    assert info["synthetic"]
    x = train["features"].reshape(len(train), -1)
    y = train["label_index"]
    # per-pixel SNR: class-delta std is far below the noise std
    class_means = np.stack([x[y == c].mean(0) for c in range(10)])
    signal = class_means.std(0).mean()
    noise = np.mean([x[y == c].std(0).mean() for c in range(10)])
    assert signal < 0.35 * noise, (signal, noise)
