"""Dataset loaders + chunked data plane."""

import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.loaders import load_cifar10, load_cifar100, load_mnist


def test_mnist_synthetic_fallback_shapes():
    train, test, info = load_mnist()
    assert info["synthetic"] is True  # offline environment
    assert train["features"].shape == (60000, 28, 28, 1)
    assert train["features"].dtype == np.float32
    assert 0.0 <= train["features"].min() and train["features"].max() <= 1.0
    assert train["label"].shape == (60000, 10)
    assert test["label_index"].shape == (10000,)


def test_mnist_flatten():
    train, _, _ = load_mnist(flatten=True)
    assert train["features"].shape == (60000, 784)


def test_cifar_shapes():
    train, test, info = load_cifar10()
    assert train["features"].shape == (50000, 32, 32, 3)
    train100, _, info100 = load_cifar100()
    assert train100["label"].shape == (50000, 100)


def test_synthetic_is_deterministic_and_learnable():
    a, _, _ = load_mnist()
    b, _, _ = load_mnist()
    np.testing.assert_array_equal(a["features"][:16], b["features"][:16])
    # nearest-prototype separability: a linear probe must beat chance easily
    x = a["features"][:2000].reshape(2000, -1)
    y = a["label_index"][:2000]
    centers = np.stack([x[y == c].mean(axis=0) for c in range(10)])
    pred = np.argmin(((x[:, None, :] - centers[None]) ** 2).sum(-1), axis=1)
    assert (pred == y).mean() > 0.9


def test_real_npz_cache_wins(tmp_path):
    x_train = np.zeros((32, 28, 28), np.uint8)
    y_train = np.arange(32) % 10
    np.savez(tmp_path / "mnist.npz", x_train=x_train, y_train=y_train,
             x_test=x_train[:8], y_test=y_train[:8])
    train, test, info = load_mnist(cache_dir=str(tmp_path))
    assert info["synthetic"] is False
    assert train["features"].shape == (32, 28, 28, 1)
    assert len(test) == 8


def test_no_fallback_raises():
    with pytest.raises(FileNotFoundError):
        load_mnist(cache_dir="/nonexistent", synthetic_fallback=False)


# -- chunked epoch -------------------------------------------------------------

def _ds(n=100):
    return Dataset({"features": np.arange(n * 3, dtype=np.float32).reshape(n, 3),
                    "label": np.arange(n, dtype=np.int32)})


def test_chunked_epoch_covers_same_rows_as_stacked():
    ds = _ds(100)
    stacked = ds.stacked_epoch(4, ["features", "label"], window=2)
    chunks = list(ds.chunked_epoch(4, ["features", "label"], window=2, chunk_windows=5))
    assert len(chunks) == 3  # 12 windows -> 5 + 5 + 2
    assert [c["features"].shape[0] for c in chunks] == [5, 5, 2]
    rejoined = np.concatenate([c["features"] for c in chunks])
    np.testing.assert_array_equal(rejoined, stacked["features"])


def test_chunked_epoch_default_is_one_chunk():
    ds = _ds(64)
    chunks = list(ds.chunked_epoch(8, ["features"], window=1))
    assert len(chunks) == 1
    assert chunks[0]["features"].shape == (8, 1, 8, 3)


def test_chunked_epoch_chunks_are_views():
    ds = _ds(64)
    (chunk,) = ds.chunked_epoch(8, ["features"], window=1, chunk_windows=8)
    assert chunk["features"].base is not None  # zero-copy reshape of a slice


def test_chunked_training_matches_unchunked():
    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.trainers import ADAG, SingleTrainer

    rng = np.random.default_rng(0)
    n = 256
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(int)
    ds = Dataset({"features": x, "label": np.eye(2, dtype=np.float32)[y]})
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))

    def run(cls, chunk_windows, **kw):
        t = cls(spec, loss="categorical_crossentropy", worker_optimizer="sgd",
                learning_rate=0.05, batch_size=8, num_epoch=2, seed=0,
                chunk_windows=chunk_windows, **kw)
        m = t.train(ds)
        return t, m

    for cls, kw in ((SingleTrainer, {}), (ADAG, {"communication_window": 2, "num_workers": 2})):
        t_full, m_full = run(cls, None, **kw)
        t_chunk, m_chunk = run(cls, 3, **kw)
        assert t_full.history == pytest.approx(t_chunk.history, rel=1e-5)
        for a, b in zip(np.asarray(list(m_full.params.values())[0]["kernel"]).ravel(),
                        np.asarray(list(m_chunk.params.values())[0]["kernel"]).ravel()):
            assert a == pytest.approx(b, rel=1e-5)
