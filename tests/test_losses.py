"""unembed_cross_entropy: chunked fused loss == dense reference, fwd + grad."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distkeras_tpu.ops.losses import _pick_chunks, unembed_cross_entropy


def _data(b=2, l=16, e=32, v=64, seed=0):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.normal(size=(b, l, e)).astype(np.float32))
    table = jnp.asarray(rng.normal(size=(v, e)).astype(np.float32) * 0.1)
    tgt = jnp.asarray(rng.integers(0, v, size=(b, l)), dtype=jnp.int32)
    return h, table, tgt


def test_pick_chunks():
    big_v = 65536  # past the dense ceiling at these row counts
    assert _pick_chunks(32, big_v, 2048) == 1       # fits in one chunk
    assert _pick_chunks(4096, big_v, 2048) == 2
    assert _pick_chunks(4096, big_v, 1000) == 8     # next divisor under target
    # awkward factorizations (prime rows: only fitting divisor means
    # near-per-row chunks) fall back to one dense chunk, never a long
    # sequential map of tiny matmuls
    assert _pick_chunks(6002, big_v, 2048) == 1     # 2 * 3001
    assert _pick_chunks(7919, big_v, 2048) == 1     # prime
    # DEFAULT policy (target None): below the dense-logits ceiling the
    # single dense chunk wins outright (measured: the chunked map's DUS +
    # checkpoint recompute cost more than materializing ~0.5GB of logits
    # once) — the 2k/8k LM legs
    assert _pick_chunks(16384, 8192, None) == 1
    # the 32k leg's 1GB logits stay chunked: memory is why chunking exists
    assert _pick_chunks(32768, 8192, None) == 16
    # an EXPLICIT chunk_rows is a caller's memory bound: honored strictly,
    # never overridden by the dense fast path
    assert _pick_chunks(16384, 8192, 2048) == 8


def test_matches_optax_dense_f32():
    h, table, tgt = _data()
    ce = unembed_cross_entropy(h, table, tgt, compute_dtype=None)
    logits = jnp.einsum("ble,ve->blv", h, table)
    ref = optax.softmax_cross_entropy_with_integer_labels(logits, tgt)
    np.testing.assert_allclose(np.asarray(ce), np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_chunked_equals_unchunked():
    h, table, tgt = _data(b=2, l=16)
    one = unembed_cross_entropy(h, table, tgt, chunk_rows=32, compute_dtype=None)
    many = unembed_cross_entropy(h, table, tgt, chunk_rows=4, compute_dtype=None)
    np.testing.assert_allclose(np.asarray(one), np.asarray(many), rtol=1e-6)


def test_bf16_path_matches_bf16_dense():
    h, table, tgt = _data(seed=1)
    ce = unembed_cross_entropy(h, table, tgt, chunk_rows=8)  # default bf16
    logits = jax.lax.dot_general(
        h.reshape(-1, h.shape[-1]).astype(jnp.bfloat16), table.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    ref = optax.softmax_cross_entropy_with_integer_labels(
        logits, tgt.reshape(-1)).reshape(tgt.shape)
    np.testing.assert_allclose(np.asarray(ce), np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_gradients_match_dense_reference():
    h, table, tgt = _data(b=2, l=8, seed=2)

    def fused(h, table):
        return unembed_cross_entropy(h, table, tgt, chunk_rows=4,
                                     compute_dtype=None).mean()

    def dense(h, table):
        logits = jnp.einsum("ble,ve->blv", h, table)
        return optax.softmax_cross_entropy_with_integer_labels(logits, tgt).mean()

    gh1, gt1 = jax.grad(fused, argnums=(0, 1))(h, table)
    gh2, gt2 = jax.grad(dense, argnums=(0, 1))(h, table)
    np.testing.assert_allclose(np.asarray(gh1), np.asarray(gh2), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gt1), np.asarray(gt2), rtol=1e-5, atol=1e-6)


def test_jit_and_nondivisible_rows():
    # rows = 2*7 = 14: only divisors 1/2/7/14 — chunking still exact
    h, table, tgt = _data(b=2, l=7, seed=3)
    fn = jax.jit(lambda h, t: unembed_cross_entropy(h, table, t, chunk_rows=3,
                                                    compute_dtype=None))
    ce = fn(h, tgt)
    logits = jnp.einsum("ble,ve->blv", h, table)
    ref = optax.softmax_cross_entropy_with_integer_labels(logits, tgt)
    np.testing.assert_allclose(np.asarray(ce), np.asarray(ref), rtol=1e-5, atol=1e-6)
