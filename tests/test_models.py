"""Model-zoo smoke tests: build, forward-shape, registry round-trip."""

import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models.base import Model, ModelSpec
from distkeras_tpu.models.cnn import mnist_cnn_spec
from distkeras_tpu.models.mlp import mnist_mlp_spec
from distkeras_tpu.models.resnet import resnet20_spec
from distkeras_tpu.models.transformer import small_lm_spec


@pytest.mark.parametrize("spec_fn,batch_shape,out_shape", [
    (mnist_mlp_spec, (2, 784), (2, 10)),
    (mnist_cnn_spec, (2, 28, 28, 1), (2, 10)),
])
def test_forward_shapes(spec_fn, batch_shape, out_shape):
    model = Model.init(spec_fn(), seed=0)
    x = np.zeros(batch_shape, dtype=np.float32)
    assert model.apply(x).shape == out_shape


@pytest.mark.slow  # tier-1 budget (ISSUE 14 satellite): 8.1 s: compiles the full ResNet-20 graph; transformer/cnn forwards keep model coverage in tier-1
def test_resnet20_forward():
    model = Model.init(resnet20_spec(num_outputs=100), seed=0)
    x = np.zeros((2, 32, 32, 3), dtype=np.float32)
    assert model.apply(x).shape == (2, 100)


def test_transformer_forward():
    spec = small_lm_spec(vocab_size=64, model_dim=32, num_heads=2, num_layers=2, max_seq_len=16)
    model = Model.init(spec, seed=0)
    tokens = np.zeros((2, 16), dtype=np.int32)
    logits = model.apply(tokens)
    assert logits.shape == (2, 16, 64)


def test_unknown_architecture_raises():
    with pytest.raises(ValueError, match="unknown architecture"):
        ModelSpec(name="nope", config={}, input_shape=(4,)).build()


def test_spec_dict_roundtrip():
    spec = mnist_cnn_spec()
    assert ModelSpec.from_dict(spec.to_dict()) == spec


@pytest.mark.slow  # tier-1 budget fix (PR 11): heaviest cells ride the full suite
def test_transformer_remat_matches_non_remat():
    """remat=True must be a pure memory trade: identical loss and grads."""
    import jax
    import jax.numpy as jnp
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.transformer import small_lm_spec

    base = small_lm_spec(vocab_size=64, model_dim=32, num_heads=2,
                         num_layers=2, max_seq_len=16)
    rem = small_lm_spec(vocab_size=64, model_dim=32, num_heads=2,
                        num_layers=2, max_seq_len=16, remat=True)
    m = Model.init(base, seed=0)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)), jnp.int32)
    tgt = jnp.roll(toks, -1, axis=1)

    def loss_for(spec):
        apply = spec.apply_fn()

        def f(p):
            logits = apply(p, toks)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), tgt).mean()

        return f

    l0, g0 = jax.value_and_grad(loss_for(base))(m.params)
    l1, g1 = jax.value_and_grad(loss_for(rem))(m.params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_transformer_attn_impl_parity():
    """``attn_impl`` pins the attention kernel without changing semantics:
    flash (interpret mode on CPU) and dense produce the same logits and
    grads, and the auto default equals dense on short CPU shapes.  Lengths
    are flash-legal (L=128 spans the whole sequence as one Mosaic block)."""
    import jax
    import jax.numpy as jnp
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.transformer import small_lm_spec

    kw = dict(vocab_size=64, model_dim=32, num_heads=2, num_layers=2,
              max_seq_len=128)
    dense = small_lm_spec(attn_impl="dense", **kw)
    flash = small_lm_spec(attn_impl="flash", **kw)
    auto = small_lm_spec(**kw)
    m = Model.init(dense, seed=0)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 128)), jnp.int32)
    tgt = jnp.roll(toks, -1, axis=1)

    def loss_for(spec):
        apply = spec.apply_fn()

        def f(p):
            logits = apply(p, toks)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), tgt).mean()

        return f

    l_dense, g_dense = jax.value_and_grad(loss_for(dense))(m.params)
    l_flash, g_flash = jax.value_and_grad(loss_for(flash))(m.params)
    l_auto = loss_for(auto)(m.params)
    # flash keeps bf16 matmuls + f32 stats vs dense's f32 softmax: a few
    # 1e-5 of relative loss drift is the expected bf16 rounding, not skew
    np.testing.assert_allclose(float(l_dense), float(l_flash), rtol=2e-4)
    np.testing.assert_allclose(float(l_dense), float(l_auto), rtol=1e-7)
    # loose bound: bf16 kernel rounding puts ~1-2% noise on small grad
    # elements; kernel-grad EXACTNESS is tests/test_flash_attention.py's
    # job — this asserts the plumbing reached a working kernel (wrong
    # math would be O(1) off)
    for a, b in zip(jax.tree.leaves(g_dense), jax.tree.leaves(g_flash)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-2, atol=2e-3)


def test_model_summary():
    import jax

    from distkeras_tpu.models.transformer import small_lm_spec

    m = Model.init(small_lm_spec(vocab_size=64, model_dim=32, num_heads=2,
                                 num_layers=2, max_seq_len=16), seed=0)
    s = m.summary()
    assert "block_0" in s and "embed" in s and "total:" in s
    want = sum(int(l.size) for l in jax.tree.leaves(m.params))
    assert f"{want:,} params" in s


@pytest.mark.slow  # tier-1 budget fix (PR 11): heaviest cells ride the full suite
def test_compute_dtype_policy_parity_classic_family():
    """bf16-compute CNN/MLP/ResNet: identical float32 param trees (the
    policy touches activations only), logits within bf16 rounding of the
    f32 forward, and one SGD train step's loss within tolerance — the LM
    stack's mixed-precision scheme extended to the parity family."""
    import jax
    import jax.numpy as jnp
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.cnn import mnist_cnn_spec
    from distkeras_tpu.models.mlp import mnist_mlp_spec
    from distkeras_tpu.models.resnet import resnet20_spec
    from distkeras_tpu.ops.losses import get_loss

    rng = np.random.default_rng(0)
    cases = [
        (mnist_cnn_spec, (8, 28, 28, 1), 10),
        (mnist_mlp_spec, (8, 784), 10),
        (resnet20_spec, (4, 32, 32, 3), 100),
    ]
    loss_fn = get_loss("categorical_crossentropy")
    for make_spec, shape, classes in cases:
        x = jnp.asarray(rng.normal(size=shape), jnp.float32)
        y = jnp.asarray(np.eye(classes, dtype=np.float32)[
            rng.integers(0, classes, size=shape[0])])
        f32 = Model.init(make_spec(), seed=0)
        bf16 = Model.init(make_spec(compute_dtype="bfloat16"), seed=0)
        # params are float32 and IDENTICAL under both policies
        for a, b in zip(jax.tree.leaves(f32.params), jax.tree.leaves(bf16.params)):
            assert a.dtype == np.float32 and b.dtype == np.float32
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        lf = np.asarray(f32.apply(x), np.float32)
        raw = np.asarray(bf16.apply(x))
        assert raw.dtype == np.float32  # head emits f32 logits (pre-cast!)
        lb = raw
        scale = max(1.0, float(np.abs(lf).max()))
        np.testing.assert_allclose(lb / scale, lf / scale, atol=3e-2,
                                   err_msg=make_spec.__name__)

        def step_loss(model):
            apply = model.spec.apply_fn()
            opt = optax.sgd(0.05)

            def obj(p):
                return loss_fn(apply(p, x), y)

            l0, g = jax.value_and_grad(obj)(model.params)
            p1 = optax.apply_updates(model.params, opt.update(g, opt.init(model.params))[0])
            return float(l0), float(obj(p1))

        (l0f, l1f), (l0b, l1b) = step_loss(f32), step_loss(bf16)
        # the two policies track each other before AND after an update
        # (one random-data SGD step is not a learning guarantee — only
        # parity and finiteness are asserted)
        assert abs(l0b - l0f) < 0.05 * max(1.0, abs(l0f)), make_spec.__name__
        assert abs(l1b - l1f) < 0.05 * max(1.0, abs(l1f)), make_spec.__name__
        assert np.isfinite([l0f, l1f, l0b, l1b]).all(), make_spec.__name__
