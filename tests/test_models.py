"""Model-zoo smoke tests: build, forward-shape, registry round-trip."""

import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models.base import Model, ModelSpec
from distkeras_tpu.models.cnn import mnist_cnn_spec
from distkeras_tpu.models.mlp import mnist_mlp_spec
from distkeras_tpu.models.resnet import resnet20_spec
from distkeras_tpu.models.transformer import small_lm_spec


@pytest.mark.parametrize("spec_fn,batch_shape,out_shape", [
    (mnist_mlp_spec, (2, 784), (2, 10)),
    (mnist_cnn_spec, (2, 28, 28, 1), (2, 10)),
])
def test_forward_shapes(spec_fn, batch_shape, out_shape):
    model = Model.init(spec_fn(), seed=0)
    x = np.zeros(batch_shape, dtype=np.float32)
    assert model.apply(x).shape == out_shape


def test_resnet20_forward():
    model = Model.init(resnet20_spec(num_outputs=100), seed=0)
    x = np.zeros((2, 32, 32, 3), dtype=np.float32)
    assert model.apply(x).shape == (2, 100)


def test_transformer_forward():
    spec = small_lm_spec(vocab_size=64, model_dim=32, num_heads=2, num_layers=2, max_seq_len=16)
    model = Model.init(spec, seed=0)
    tokens = np.zeros((2, 16), dtype=np.int32)
    logits = model.apply(tokens)
    assert logits.shape == (2, 16, 64)


def test_unknown_architecture_raises():
    with pytest.raises(ValueError, match="unknown architecture"):
        ModelSpec(name="nope", config={}, input_shape=(4,)).build()


def test_spec_dict_roundtrip():
    spec = mnist_cnn_spec()
    assert ModelSpec.from_dict(spec.to_dict()) == spec


def test_transformer_remat_matches_non_remat():
    """remat=True must be a pure memory trade: identical loss and grads."""
    import jax
    import jax.numpy as jnp
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.transformer import small_lm_spec

    base = small_lm_spec(vocab_size=64, model_dim=32, num_heads=2,
                         num_layers=2, max_seq_len=16)
    rem = small_lm_spec(vocab_size=64, model_dim=32, num_heads=2,
                        num_layers=2, max_seq_len=16, remat=True)
    m = Model.init(base, seed=0)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)), jnp.int32)
    tgt = jnp.roll(toks, -1, axis=1)

    def loss_for(spec):
        apply = spec.apply_fn()

        def f(p):
            logits = apply(p, toks)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), tgt).mean()

        return f

    l0, g0 = jax.value_and_grad(loss_for(base))(m.params)
    l1, g1 = jax.value_and_grad(loss_for(rem))(m.params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_model_summary():
    import jax

    from distkeras_tpu.models.transformer import small_lm_spec

    m = Model.init(small_lm_spec(vocab_size=64, model_dim=32, num_heads=2,
                                 num_layers=2, max_seq_len=16), seed=0)
    s = m.summary()
    assert "block_0" in s and "embed" in s and "total:" in s
    want = sum(int(l.size) for l in jax.tree.leaves(m.params))
    assert f"{want:,} params" in s
