"""Expert parallelism (Switch-style MoE over an ``ep`` mesh axis).

No reference counterpart (SURVEY §2.13: data-parallel only) — these pin
down the TPU-native guarantees: expert-parallel execution matches the
single-device computation, capacity drops are deterministic, and the
(dp x ep) train step learns.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu.parallel.mesh import create_nd_mesh
from distkeras_tpu.parallel.moe import (
    MoEMLP, _moe_param_specs, dispatch_matmul_flops, make_moe_train_step,
    moe_classifier_spec, moe_data_sharding, moe_state_shardings,
    resolve_dispatch_impl)

T, D, E, F = 64, 16, 4, 32


def _moe(capacity, ep_axis=None, ep_size=1):
    return MoEMLP(num_experts=E, model_dim=D, hidden_dim=F, capacity=capacity,
                  ep_axis=ep_axis, ep_size=ep_size, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def tokens_and_params():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(T, D)), dtype=jnp.float32)
    params = _moe(capacity=T).init(jax.random.PRNGKey(0), x)["params"]
    return x, params


def test_expert_parallel_matches_single_device(tokens_and_params):
    """ep=4 all_to_all dispatch + SHARDED expert weights == all-experts-local,
    when nothing drops."""
    x, params = tokens_and_params
    ref, aux_ref = _moe(capacity=T).apply({"params": params}, x)

    mesh = create_nd_mesh((4,), ("ep",))
    # capacity is per shard; T >> T/4 so no drops
    mod = _moe(capacity=T, ep_axis="ep", ep_size=4)
    pspecs = _moe_param_specs(params, "ep")

    def fn(params, x):
        out, aux = mod.apply({"params": params}, x)
        return out, jax.lax.psum(aux, "ep") / jax.lax.psum(1, "ep")

    sharded = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=(pspecs, P("ep")),
                                    out_specs=(P("ep"), P())))
    psh = jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs,
                       is_leaf=lambda v: isinstance(v, P))
    out = sharded(jax.device_put(params, psh),
                  jax.device_put(x, NamedSharding(mesh, P("ep"))))
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(ref), rtol=2e-4, atol=2e-5)
    # aux is per-shard token-fraction based; with tokens split evenly the
    # mean of shard-auxes equals the global aux only when routing fractions
    # match per shard — just require finiteness + same scale here
    assert np.isfinite(float(out[1]))


def test_capacity_drop_is_deterministic_residual():
    """Tokens beyond an expert's capacity contribute exactly zero output."""
    rng = np.random.default_rng(1)
    # positive-sum rows so a large positive router column forces expert 0
    x = jnp.asarray(rng.normal(size=(8, D)) + 2.0, dtype=jnp.float32)
    mod = _moe(capacity=2)
    params = mod.init(jax.random.PRNGKey(0), x)["params"]
    router = np.zeros((D, E), np.float32)
    router[:, 0] = 1e3
    params = dict(params, router=jnp.asarray(router))
    out, aux = mod.apply({"params": params}, x)
    out = np.asarray(out)
    # first 2 tokens fill expert 0's queue; the rest are dropped -> zero rows
    assert np.abs(out[:2]).sum() > 0
    np.testing.assert_array_equal(out[2:], np.zeros_like(out[2:]))
    # aux loss sees the imbalance: all mass on one expert -> ~E * 1 * p_0
    assert float(aux) > 1.0


def test_moe_train_step_learns_dp_ep():
    mesh = create_nd_mesh((2, 2), ("dp", "ep"))
    spec = moe_classifier_spec(input_dim=D, num_experts=E, capacity=32, num_outputs=4)
    opt = optax.adam(3e-3)
    step = make_moe_train_step(spec, opt, mesh)

    rng = np.random.default_rng(2)
    centers = rng.normal(scale=2.5, size=(4, D))
    labels = rng.integers(0, 4, size=128)
    x = (centers[labels] + rng.normal(scale=0.5, size=(128, D))).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[labels]

    params = jax.tree.map(jnp.asarray, spec.init_params(seed=0))
    psh, osh = moe_state_shardings(mesh, opt, params)
    params = jax.device_put(params, psh)
    opt_state = jax.device_put(opt.init(params), osh)
    # expert slabs really are distributed: each device holds E/ep experts
    w_up = params["moe"]["w_up"]
    assert w_up.sharding.spec == P("ep")
    assert w_up.addressable_shards[0].data.shape[0] == E // 2
    dsh = moe_data_sharding(mesh)
    xd, yd = jax.device_put(jnp.asarray(x), dsh), jax.device_put(jnp.asarray(y), dsh)

    losses = []
    for _ in range(30):
        params, opt_state, loss, stats = step(params, opt_state, xd, yd)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]
    # router observability comes back with every step
    assert set(stats) == {"dropped_fraction", "max_expert_load"}
    assert 0.0 <= float(stats["dropped_fraction"]) <= 1.0
    assert float(stats["max_expert_load"]) >= 0.0


def _dense_routing_oracle(x, params, capacity, top_k):
    """Numpy re-derivation of the routed MoE forward: softmax router,
    top-k choices with rank-priority seating, gelu expert MLPs, gate-
    weighted combine.  Independent of the einsum/one-hot implementation."""
    import scipy.special as sp

    x64 = np.asarray(x, np.float64)
    router = np.asarray(params["router"], np.float64)
    w_up = np.asarray(params["w_up"], np.float64)
    w_down = np.asarray(params["w_down"], np.float64)
    scores = sp.softmax(x64 @ router, axis=-1)
    t, e = scores.shape
    order = np.argsort(-scores, axis=-1)[:, :top_k]   # [T, k]
    gates = np.take_along_axis(scores, order, axis=-1)
    if top_k > 1:
        gates = gates / gates.sum(-1, keepdims=True)
    counts = np.zeros(e, np.int64)
    out = np.zeros_like(x64)
    seated = []  # (token, expert, gate), rank-major like the kernel
    for r in range(top_k):
        for tok in range(t):
            exp = order[tok, r]
            if counts[exp] < capacity:
                seated.append((tok, exp, gates[tok, r]))
                counts[exp] += 1
    for tok, exp, g in seated:
        h = x64[tok] @ w_up[exp]
        # flax nn.gelu default: the tanh approximation
        h = 0.5 * h * (1.0 + np.tanh(np.sqrt(2.0 / np.pi)
                                     * (h + 0.044715 * h ** 3)))
        out[tok] += g * (h @ w_down[exp])
    return out


@pytest.mark.parametrize("top_k", [1, 2])
def test_routing_matches_dense_oracle(top_k):
    """The one-hot einsum dispatch equals a loop-and-gather oracle for
    both Switch (k=1) and top-2 routing, including capacity drops with
    rank priority."""
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(24, D)), dtype=jnp.float32)
    mod = MoEMLP(num_experts=E, model_dim=D, hidden_dim=F, capacity=5,
                 router_top_k=top_k, compute_dtype=jnp.float32)
    params = mod.init(jax.random.PRNGKey(2), x)["params"]
    got, _ = mod.apply({"params": params}, x)
    want = _dense_routing_oracle(x, params, capacity=5, top_k=top_k)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-5)


def test_top2_expert_parallel_matches_single_device(tokens_and_params):
    """The top-2 ep=4 all_to_all path equals all-experts-local — routing
    depends only on (params, tokens), so sharding must not change it."""
    x, _ = tokens_and_params
    mod1 = MoEMLP(num_experts=E, model_dim=D, hidden_dim=F, capacity=T,
                  router_top_k=2, compute_dtype=jnp.float32)
    params = mod1.init(jax.random.PRNGKey(1), x)["params"]
    ref, _ = mod1.apply({"params": params}, x)

    mesh = create_nd_mesh((4,), ("ep",))
    mod4 = MoEMLP(num_experts=E, model_dim=D, hidden_dim=F, capacity=T,
                  router_top_k=2, ep_axis="ep", ep_size=4,
                  compute_dtype=jnp.float32)
    pspecs = _moe_param_specs(params, "ep")

    def fn(params, x):
        out, _ = mod4.apply({"params": params}, x)
        return out

    sharded = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=(pspecs, P("ep")),
                                    out_specs=P("ep")))
    psh = jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs,
                       is_leaf=lambda v: isinstance(v, P))
    out = sharded(jax.device_put(params, psh),
                  jax.device_put(x, NamedSharding(mesh, P("ep"))))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("top_k", [1, 2])
@pytest.mark.parametrize("cap", [5, T])  # 5: heavy drops; T: no drops
def test_sorted_dispatch_bit_parity(top_k, cap):
    """The sorted (scatter/gather) dispatch must be BIT-identical to the
    dense one-hot einsums — outputs, aux loss, and gradients — for both
    routing modes and capacities with and without drops.  Parity by
    construction: the two impls share the seating computation and differ
    only in how rows move; the combine contraction runs through the same
    dot/FMA machinery on both sides."""
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(24, D)), dtype=jnp.float32)

    def mk(impl):
        return MoEMLP(num_experts=E, model_dim=D, hidden_dim=F, capacity=cap,
                      router_top_k=top_k, dispatch_impl=impl,
                      compute_dtype=jnp.float32)

    dense, srt = mk("dense"), mk("sorted")
    params = dense.init(jax.random.PRNGKey(top_k), x)["params"]
    out_d, aux_d = dense.apply({"params": params}, x)
    out_s, aux_s = srt.apply({"params": params}, x)
    np.testing.assert_array_equal(np.asarray(out_s), np.asarray(out_d))
    assert float(aux_s) == float(aux_d)

    def loss(p, mod):
        out, aux = mod.apply({"params": p}, x)
        return jnp.sum(out ** 2) + aux

    g_d = jax.grad(loss)(params, dense)
    g_s = jax.grad(loss)(params, srt)
    for name in g_d:
        np.testing.assert_allclose(
            np.asarray(g_s[name]), np.asarray(g_d[name]), rtol=1e-6, atol=1e-7,
            err_msg=f"grad mismatch for {name}")


def test_sorted_dispatch_bit_parity_bf16():
    """Same parity under the production compute dtype: compute-dtype
    operands, f32 accumulation, one downcast on both paths."""
    rng = np.random.default_rng(12)
    x = jnp.asarray(rng.normal(size=(32, D)), dtype=jnp.float32)
    outs = []
    for impl in ("dense", "sorted"):
        mod = MoEMLP(num_experts=E, model_dim=D, hidden_dim=F, capacity=8,
                     router_top_k=2, dispatch_impl=impl)
        params = mod.init(jax.random.PRNGKey(3), x)["params"]
        outs.append(np.asarray(mod.apply({"params": params}, x)[0]))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_sorted_expert_parallel_matches_dense_single_device(tokens_and_params):
    """ep=4 sorted dispatch (all_to_all + sharded experts) == ep=1 dense:
    the two dispatch paths and the two shardings are ONE math."""
    if not hasattr(jax, "shard_map"):
        pytest.skip("jax.shard_map unavailable in this environment")
    x, params = tokens_and_params
    ref, _ = _moe(capacity=T).apply({"params": params}, x)  # dense, ep=1

    mesh = create_nd_mesh((4,), ("ep",))
    mod = MoEMLP(num_experts=E, model_dim=D, hidden_dim=F, capacity=T,
                 ep_axis="ep", ep_size=4, dispatch_impl="sorted",
                 compute_dtype=jnp.float32)
    pspecs = _moe_param_specs(params, "ep")

    def fn(params, x):
        out, _ = mod.apply({"params": params}, x)
        return out

    sharded = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=(pspecs, P("ep")),
                                    out_specs=P("ep")))
    psh = jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs,
                       is_leaf=lambda v: isinstance(v, P))
    out = sharded(jax.device_put(params, psh),
                  jax.device_put(x, NamedSharding(mesh, P("ep"))))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("top_k", [1, 2])
def test_sorted_ep4_bit_matches_dense_ep4(top_k):
    """ep=4 sorted == ep=4 dense BIT-for-bit (same sharding, same seating,
    only the row movement differs — k=1 and k=2)."""
    if not hasattr(jax, "shard_map"):
        pytest.skip("jax.shard_map unavailable in this environment")
    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.normal(size=(T, D)), dtype=jnp.float32)
    mesh = create_nd_mesh((4,), ("ep",))
    outs = []
    params = None
    for impl in ("dense", "sorted"):
        mod = MoEMLP(num_experts=E, model_dim=D, hidden_dim=F, capacity=8,
                     router_top_k=top_k, ep_axis="ep", ep_size=4,
                     dispatch_impl=impl, compute_dtype=jnp.float32)
        if params is None:
            init_mod = MoEMLP(num_experts=E, model_dim=D, hidden_dim=F,
                              capacity=8, router_top_k=top_k,
                              dispatch_impl=impl, compute_dtype=jnp.float32)
            params = init_mod.init(jax.random.PRNGKey(5), x)["params"]
        pspecs = _moe_param_specs(params, "ep")

        def fn(params, x, mod=mod):
            out, _ = mod.apply({"params": params}, x)
            return out

        sharded = jax.jit(jax.shard_map(fn, mesh=mesh,
                                        in_specs=(pspecs, P("ep")),
                                        out_specs=P("ep")))
        psh = jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs,
                           is_leaf=lambda v: isinstance(v, P))
        outs.append(np.asarray(sharded(
            jax.device_put(params, psh),
            jax.device_put(x, NamedSharding(mesh, P("ep"))))))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_resolve_dispatch_impl_and_flops():
    """Auto keys on the dense one-hot tensor size T*E*C; explicit impls
    pass through; dispatch FLOPs: 4·T·E·C·D dense, 0 sorted."""
    assert resolve_dispatch_impl("dense", 10**6, 64, 10**4) == "dense"
    assert resolve_dispatch_impl("sorted", 2, 2, 2) == "sorted"
    assert resolve_dispatch_impl("auto", 64, 4, 64) == "dense"   # 16k elems
    assert resolve_dispatch_impl("auto", 2048, 8, 512) == "sorted"  # 8.4M
    with pytest.raises(ValueError, match="dispatch_impl"):
        resolve_dispatch_impl("blocked", 1, 1, 1)
    assert dispatch_matmul_flops(2048, 8, 512, 512, "dense") == \
        4 * 2048 * 8 * 512 * 512
    assert dispatch_matmul_flops(2048, 8, 512, 512, "sorted") == 0
    with pytest.raises(ValueError, match="impl"):
        dispatch_matmul_flops(1, 1, 1, 1, "auto")


def test_dispatch_flops_pct_is_reported():
    """Regression (issue 2 satellite): the sown router stats must carry
    ``dispatch_flops_pct`` — ~0 on the sorted path, > 0 on dense — so the
    train steps and telemetry gauges actually surface the dispatch tax."""
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(T, D)), dtype=jnp.float32)
    for impl, check in (("dense", lambda v: v > 0.0),
                        ("sorted", lambda v: v == 0.0)):
        mod = MoEMLP(num_experts=E, model_dim=D, hidden_dim=F, capacity=16,
                     dispatch_impl=impl, compute_dtype=jnp.float32)
        params = mod.init(jax.random.PRNGKey(0), x)["params"]
        _, variables = mod.apply({"params": params}, x,
                                 mutable=["router_stats"])
        stats = variables["router_stats"]
        assert "dispatch_flops_pct" in stats
        pct = float(jax.tree.leaves(stats["dispatch_flops_pct"])[0])
        assert 0.0 <= pct < 100.0
        assert check(pct), (impl, pct)


def test_dispatch_flops_pct_in_train_step_stats():
    """The (dp x ep) train step's returned router_stats include the
    dispatch pct (and the telemetry gauge path reads the same dict)."""
    if not hasattr(jax, "shard_map"):
        pytest.skip("jax.shard_map unavailable in this environment")
    mesh = create_nd_mesh((2, 2), ("dp", "ep"))
    spec = moe_classifier_spec(input_dim=D, num_experts=E, capacity=32,
                               num_outputs=4, dispatch_impl="sorted")
    opt = optax.sgd(0.01)
    step = make_moe_train_step(spec, opt, mesh)
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(64, D)), dtype=jnp.float32)
    y = jnp.asarray(np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)])
    params = jax.tree.map(jnp.asarray, spec.init_params(seed=0))
    psh, osh = moe_state_shardings(mesh, opt, params)
    params = jax.device_put(params, psh)
    opt_state = jax.device_put(opt.init(params), osh)
    dsh = moe_data_sharding(mesh)
    _, _, _, stats = step(params, opt_state, jax.device_put(x, dsh),
                          jax.device_put(y, dsh))
    assert set(stats) >= {"dropped_fraction", "max_expert_load",
                          "dispatch_flops_pct"}
    assert float(stats["dispatch_flops_pct"]) == 0.0  # sorted path


def test_trained_router_drops_below_5pct():
    """With the load-balance aux in the objective, a TRAINED router at
    factor-2 capacity must drop < 5% of assignments (the recorded 18-30%
    drops were untrained-router worst cases — issue 2 satellite).  Single
    device, sorted dispatch, fresh random batches each step so balance
    generalizes rather than memorizes."""
    t, cap_factor = 64, 2.0
    cap = int(cap_factor * t) // E
    mod = MoEMLP(num_experts=E, model_dim=D, hidden_dim=F, capacity=cap,
                 dispatch_impl="sorted", compute_dtype=jnp.float32)
    rng = np.random.default_rng(8)
    steps = 120
    xs = jnp.asarray(rng.normal(size=(steps, t, D)), dtype=jnp.float32)
    params = mod.init(jax.random.PRNGKey(0), xs[0])["params"]
    opt = optax.adam(3e-3)

    def loss_fn(p, x):
        (out, aux), variables = mod.apply({"params": p}, x,
                                          mutable=["router_stats"])
        # reconstruction-flavored objective keeps the experts busy; the
        # aux term is what the drop assertion is about
        recon = jnp.mean((out - x) ** 2)
        dropped = jax.tree.leaves(
            variables["router_stats"]["dropped_fraction"])[0]
        return recon + 0.01 * aux, dropped

    @jax.jit
    def train(params, opt_state, xs):
        def body(carry, x):
            params, opt_state = carry
            (_, dropped), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, x)
            updates, opt_state = opt.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), dropped

        _, drops = jax.lax.scan(body, (params, opt_state), xs)
        return drops

    drops = np.asarray(train(params, opt.init(params), xs))
    assert np.isfinite(drops).all()
    assert float(np.mean(drops[-10:])) < 0.05, drops[-10:]


def test_router_counters_see_forced_overflow():
    """Route everything at one expert with tiny capacity: the sown
    counters must report the drops and the hot expert's load."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(8, D)) + 2.0, dtype=jnp.float32)
    mod = _moe(capacity=2)
    params = mod.init(jax.random.PRNGKey(0), x)["params"]
    router = np.zeros((D, E), np.float32)
    router[:, 0] = 1e3
    params = dict(params, router=jnp.asarray(router))
    (out, aux), variables = mod.apply({"params": params}, x,
                                      mutable=["router_stats"])
    stats = variables["router_stats"]
    dropped = float(jax.tree.leaves(stats["dropped_fraction"])[0])
    load = float(jax.tree.leaves(stats["max_expert_load"])[0])
    # 8 tokens -> expert 0, capacity 2: 6 of 8 dropped, load 8/2 = 4
    assert dropped == pytest.approx(6 / 8)
    assert load == pytest.approx(4.0)


def test_moe_classifier_spec_roundtrip_and_predict():
    from distkeras_tpu.models.base import Model

    spec = moe_classifier_spec(input_dim=D, num_experts=E, capacity=16, num_outputs=3)
    m = Model.init(spec, seed=0)
    x = np.random.default_rng(3).normal(size=(10, D)).astype(np.float32)
    out = m.predict(x)
    assert out.shape == (10, 3)
    m2 = Model.deserialize(m.serialize())
    np.testing.assert_array_equal(m2.predict(x), out)


def test_moe_transformer_lm_learns_dp_ep():
    """Switch MoE inside the flagship TransformerLM: (dp x ep) step with
    expert slabs sharded, per-block aux losses in the objective."""
    from distkeras_tpu.models.transformer import small_lm_spec
    from distkeras_tpu.parallel.moe import make_moe_lm_train_step, moe_state_shardings

    mesh = create_nd_mesh((2, 2), ("dp", "ep"))
    spec = small_lm_spec(vocab_size=64, model_dim=32, num_heads=2,
                         num_layers=2, max_seq_len=16,
                         moe_experts=4, moe_capacity=64)
    opt = optax.adam(3e-3)
    step = make_moe_lm_train_step(spec, opt, mesh)

    params = jax.tree.map(jnp.asarray, spec.init_params(seed=0))
    # MoE params landed inside every block
    assert "moe" in params["block_0"] and "w_up" in params["block_0"]["moe"]
    psh, osh = moe_state_shardings(mesh, opt, params)
    params = jax.device_put(params, psh)
    opt_state = jax.device_put(opt.init(params), osh)
    # expert slabs distributed: each device holds 4/2 = 2 experts
    w_up = params["block_0"]["moe"]["w_up"]
    assert w_up.addressable_shards[0].data.shape[0] == 2

    rng = np.random.default_rng(0)
    toks = rng.integers(0, 8, size=(8, 16)).astype(np.int32)

    from distkeras_tpu.parallel.moe import moe_data_sharding

    dsh = moe_data_sharding(mesh)
    tok_d = jax.device_put(jnp.asarray(toks), dsh)
    tgt_d = jax.device_put(jnp.asarray(
        np.roll(toks, -1, axis=1)), dsh)

    losses = []
    for _ in range(25):
        params, opt_state, loss, stats = step(params, opt_state, tok_d, tgt_d)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses[:3] + losses[-3:]
    assert 0.0 <= float(stats["dropped_fraction"]) <= 1.0


def test_moe_lm_single_device_forward():
    """A MoE LM spec must also run unsharded (init / eval / serialization)."""
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.transformer import small_lm_spec

    spec = small_lm_spec(vocab_size=64, model_dim=32, num_heads=2,
                         num_layers=2, max_seq_len=16, moe_experts=2)
    m = Model.init(spec, seed=0)
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 64, (2, 16)), jnp.int32)
    logits = m.apply(toks)
    assert logits.shape == (2, 16, 64)
    assert np.isfinite(np.asarray(logits, dtype=np.float32)).all()
    m2 = Model.deserialize(m.serialize())
    np.testing.assert_array_equal(np.asarray(m2.apply(toks)), np.asarray(logits))


def test_dense_lm_step_rejects_moe_spec():
    """The dense tp/sp step would drop MoE aux losses silently; it must
    refuse MoE specs and point at make_moe_lm_train_step."""
    import optax as _optax

    from distkeras_tpu.models.transformer import small_lm_spec
    from distkeras_tpu.parallel.lm import make_lm_train_step
    from distkeras_tpu.parallel.mesh import create_nd_mesh as _mesh

    spec = small_lm_spec(vocab_size=64, model_dim=32, num_heads=2,
                         num_layers=2, max_seq_len=16, moe_experts=4)
    with pytest.raises(ValueError, match="make_moe_lm_train_step"):
        make_lm_train_step(spec, _optax.sgd(0.01), _mesh((2,), ("dp",)),
                           sp_axis=None)


def test_generic_training_paths_reject_moe_spec():
    """Every spec-aware training entry that would run the plain apply_fn —
    the trainer family, the ZeRO step, the window engine, the pp step —
    must refuse MoE specs the same way the dense LM step does (a silent
    sow no-op would train with zero load-balance loss)."""
    import optax as _optax

    from distkeras_tpu.models.transformer import small_lm_spec
    from distkeras_tpu.ops.losses import get_loss
    from distkeras_tpu.parallel.algorithms import AdagAlgorithm
    from distkeras_tpu.parallel.engine import WindowEngine
    from distkeras_tpu.parallel.mesh import create_nd_mesh as _mesh
    from distkeras_tpu.parallel.pipeline import make_pp_train_step
    from distkeras_tpu.parallel.zero import make_zero_train_step
    from distkeras_tpu.trainers import SingleTrainer

    spec = small_lm_spec(vocab_size=64, model_dim=32, num_heads=2,
                         num_layers=2, max_seq_len=16, moe_experts=4)
    loss = get_loss("categorical_crossentropy")
    mesh = _mesh((2,), ("replica",))
    with pytest.raises(ValueError, match="make_moe_lm_train_step"):
        SingleTrainer(spec)
    with pytest.raises(ValueError, match="make_moe_lm_train_step"):
        make_zero_train_step(spec, loss, _optax.sgd(0.01), mesh)
    with pytest.raises(ValueError, match="make_moe_lm_train_step"):
        WindowEngine(spec, loss, _optax.sgd(0.01), AdagAlgorithm(), mesh)
    from distkeras_tpu.parallel.moe import moe_classifier_spec
    with pytest.raises(ValueError, match="make_moe_train_step"):
        SingleTrainer(moe_classifier_spec())
    with pytest.raises(ValueError, match="pipeline parallelism"):
        make_pp_train_step(spec, _optax.sgd(0.01), _mesh((2,), ("pp",)), num_microbatches=2)
