"""Multi-host execution — real separate processes (SURVEY §2.14).

The reference scaled out via Spark executors + a driver-side TCP hub; the
TPU-native equivalents are (a) SPMD multi-host through
``jax.distributed`` and (b) the async PS topology with a standalone hub.
Both are exercised here with genuine OS processes on CPU — 2 processes
standing in for 2 TPU hosts (the CI shape the round-1 verdict demanded
instead of docstring claims).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TESTS_DIR)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _child_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    # children pin their own CPU platform; scrub the parent's device-count
    # flag so each child controls its own local device count
    env.pop("XLA_FLAGS", None)
    return env


def _run_children(cmds, timeout=240):
    procs = [subprocess.Popen(c, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                              text=True, env=_child_env()) for c in cmds]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child {p.args} failed:\n{out}"
    return outs


def test_two_process_spmd_mesh():
    """2 processes x 2 CPU devices join one JAX runtime; a data-parallel
    SGD step pmean's gradients across the process boundary and both
    processes converge to identical replicated weights."""
    port = _free_port()
    script = os.path.join(_TESTS_DIR, "multihost_child_spmd.py")
    outs = _run_children([[sys.executable, script, str(i), "2", str(port)]
                          for i in range(2)])
    ws = []
    for out in outs:
        ok = [l for l in out.splitlines() if l.startswith("OK proc=")]
        assert ok, out
        assert "devices=4" in ok[0]
        ws.append(ok[0].split("w=")[1])
    # identical final weights on both processes == the collective really
    # synchronized them
    assert ws[0] == ws[1]


def test_async_ps_across_processes(tmp_path):
    """Standalone PS hub in this process; 2 worker-only Async trainers in
    separate processes commit against it (the head-node/worker-host
    topology of the async multi-host design)."""
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.runtime.launcher import start_parameter_server
    from distkeras_tpu.utils import flatten_weights

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    model = Model.init(spec, seed=0)
    flat0, _ = flatten_weights(model.params)
    ps = start_parameter_server(model, mode="delta", host="127.0.0.1")
    try:
        rng = np.random.default_rng(0)
        n = 512
        x = np.concatenate([rng.normal(-1.5, 1.0, (n // 2, 8)),
                            rng.normal(+1.5, 1.0, (n // 2, 8))]).astype(np.float32)
        y = np.concatenate([np.zeros(n // 2, int), np.ones(n // 2, int)])
        perm = rng.permutation(n)
        np.savez(tmp_path / "data.npz", features=x[perm],
                 label=np.eye(2, dtype=np.float32)[y[perm]])

        script = os.path.join(_TESTS_DIR, "multihost_child_worker.py")
        outs = _run_children(
            [[sys.executable, script, str(ps.port), str(i), "2",
              str(tmp_path / "data.npz")] for i in range(2)])
        for out in outs:
            assert any(l.startswith("OK shard=") for l in out.splitlines()), out

        assert ps.num_updates > 0
        final = ps.get_weights()
        moved = sum(float(np.abs(f - i).sum()) for f, i in zip(final, flat0))
        assert moved > 0, "remote workers' commits never reached the hub"
    finally:
        ps.stop()


def test_worker_only_mode_requires_reachable_hub():
    """ps_address pointing nowhere fails fast instead of hanging."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.runtime.async_trainer import AsyncDOWNPOUR

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 2},
                     input_shape=(4,))
    ds = Dataset({"features": np.zeros((64, 4), np.float32),
                  "label": np.eye(2, dtype=np.float32)[np.zeros(64, int)]})
    trainer = AsyncDOWNPOUR(spec, num_workers=1, ps_address=("127.0.0.1", _free_port()),
                            batch_size=16, num_epoch=1)
    with pytest.raises(ConnectionError):
        trainer.train(ds)


def test_two_process_engine_adag_matches_single_process():
    """The round-2 verdict's gap closed: the SYNC trainer family
    (DistributedTrainer -> WindowEngine) trains across a real process
    boundary — 2 processes x 2 CPU devices forming one 4-replica mesh —
    and reproduces the single-process 4-replica run exactly (same data,
    shuffle off): identical per-window losses and center weights."""
    import json

    port = _free_port()
    cmds = [[sys.executable, os.path.join(_TESTS_DIR, "multihost_child_engine.py"),
             str(i), "2", str(port)] for i in range(2)]
    outs = _run_children(cmds)

    results = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, f"child output missing RESULT line:\n{out}"
        results.append(json.loads(lines[0][len("RESULT "):]))

    # both processes must agree (the state is one global mesh program)
    assert results[0]["losses"] == results[1]["losses"]
    np.testing.assert_allclose(results[0]["center_digest"],
                               results[1]["center_digest"], rtol=1e-6)
    # AveragingTrainer's compiled cross-host mean + the in-program
    # steady-state measurement both crossed the process boundary too
    np.testing.assert_allclose(results[0]["avg_sum"], results[1]["avg_sum"],
                               rtol=1e-6)
    assert results[0]["steady_rate_positive"] and results[1]["steady_rate_positive"]

    # single-process 4-replica reference on the same data
    from tests.multihost_engine_common import make_toy, run_adag

    losses_ref, center_ref = run_adag(make_toy(), num_workers=4)
    np.testing.assert_allclose(results[0]["losses"], losses_ref, rtol=1e-5,
                               atol=1e-7)
    np.testing.assert_allclose(
        results[0]["center_sum"],
        float(sum(np.abs(w).sum() for w in center_ref)), rtol=1e-5)


def test_two_process_engine_elastic_family_matches_single_process():
    """Round-3 weak #5 closed: the elastic family's distinctive state
    crosses a real process boundary.  AEASGD keeps per-replica DIVERGENT
    local weights (SURVEY §7 "hard parts" memory layout — replicas 0/1
    live on process 0, replicas 2/3 on process 1) and DynSGD scales each
    replica's commit by its rank; both must reproduce the single-process
    4-replica run exactly: same per-window losses, same center, and the
    SAME per-replica local-norm vector."""
    import json

    port = _free_port()
    cmds = [[sys.executable, os.path.join(_TESTS_DIR, "multihost_child_elastic.py"),
             str(i), "2", str(port)] for i in range(2)]
    outs = _run_children(cmds)

    results = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, f"child output missing RESULT line:\n{out}"
        results.append(json.loads(lines[0][len("RESULT "):]))

    from tests.multihost_engine_common import make_toy, run_engine

    for kind in ("aeasgd", "dynsgd"):
        a, b = results[0][kind], results[1][kind]
        # both processes observe one global mesh program
        assert a["losses"] == b["losses"], kind
        np.testing.assert_allclose(a["center_digest"], b["center_digest"],
                                   rtol=1e-6, err_msg=kind)
        np.testing.assert_allclose(a["local_norms"], b["local_norms"],
                                   rtol=1e-6, err_msg=kind)

        # single-process 4-replica reference on the same data
        losses_ref, center_ref, norms_ref = run_engine(kind, make_toy(),
                                                       num_workers=4)
        np.testing.assert_allclose(a["losses"], losses_ref, rtol=1e-5,
                                   atol=1e-7, err_msg=kind)
        np.testing.assert_allclose(
            a["center_sum"], float(sum(np.abs(w).sum() for w in center_ref)),
            rtol=1e-5, err_msg=kind)
        np.testing.assert_allclose(a["local_norms"], norms_ref, rtol=1e-4,
                                   err_msg=kind)

    # AEASGD's locals must actually have DIVERGED (each replica trained a
    # different data shard and the elastic pull keeps them distinct);
    # DynSGD resets locals to the center every window, so no such claim.
    # Minimum pairwise norm gap, not exact distinctness: the measured gap
    # on this config is ~0.025, so 1e-3 has 25x margin while staying far
    # above float/rounding noise (a coincidental-equal-norms pass is the
    # only false negative left, and the cross-process parity asserts above
    # already pin the exact per-replica values)
    aeasgd_norms = results[0]["aeasgd"]["local_norms"]
    min_gap = min(abs(a - b) for i, a in enumerate(aeasgd_norms)
                  for b in aeasgd_norms[i + 1:])
    assert min_gap > 1e-3, f"AEASGD locals did not diverge: {aeasgd_norms}"


def test_two_process_checkpoint_resume_and_ensemble(tmp_path):
    """The engine's last multi-process gaps closed: a checkpoint written on
    a 2-process mesh (compiled all-gather; process 0 writes the shared
    spool) resumes BIT-EXACTLY — the resumed run's losses continue the
    uninterrupted run's tail and the centers agree — and EnsembleTrainer
    returns the full 4-replica ensemble identically on both processes."""
    import json

    port = _free_port()
    ckdir = str(tmp_path / "ckpt")
    cmds = [[sys.executable, os.path.join(_TESTS_DIR, "multihost_child_ckpt.py"),
             str(i), "2", str(port), ckdir] for i in range(2)]
    outs = _run_children(cmds)

    results = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, f"child output missing RESULT line:\n{out}"
        results.append(json.loads(lines[0][len("RESULT "):]))

    a, b = results
    assert a["epochs_done"] == 3  # resumed run kept checkpointing
    # both processes observed the same global program
    assert a["ref_losses"] == b["ref_losses"]
    assert a["resumed_losses"] == b["resumed_losses"]
    assert a["ensemble_sums"] == b["ensemble_sums"]
    # bit-exact resume: the resumed losses are exactly the uninterrupted
    # run's tail (epochs 1-2), and the centers agree
    n_tail = len(a["resumed_losses"])
    assert n_tail > 0
    assert a["resumed_losses"] == a["ref_losses"][-n_tail:]
    np.testing.assert_allclose(a["resumed_center_sum"], a["ref_center_sum"],
                               rtol=1e-6)
    # the ensemble really is per-replica distinct (divergent seeds)
    assert len(set(a["ensemble_sums"])) == 4
