"""Native (C++) parameter-server hub tests: the Python PSClient drives the
C++ server over the shared wire protocol, and results must match the
pure-Python hub bit-for-bit on deterministic schedules."""

import threading

import numpy as np
import pytest

from distkeras_tpu.runtime.native import (
    MODE_ADAG,
    MODE_DELTA,
    MODE_DYNSGD,
    NativeParameterServer,
    build_error,
    native_available,
)
from distkeras_tpu.runtime.parameter_server import PSClient

pytestmark = pytest.mark.skipif(
    not native_available(), reason=f"native PS unavailable: {build_error()}")


@pytest.fixture
def fresh_health():
    """Clean process-default collector/monitor (the native wrapper's poll
    thread folds wire reports into these)."""
    from distkeras_tpu.observability import health as health_mod

    health_mod.reset_default()
    yield health_mod
    health_mod.reset_default()


def _weights():
    return [np.zeros((2, 2), np.float32), np.zeros((3,), np.float32)]


def test_native_pull_commit_roundtrip():
    ps = NativeParameterServer(_weights(), mode=MODE_DELTA)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights()) as c:
            w = c.pull()
            assert all(np.all(x == 0) for x in w)
            c.commit([np.ones((2, 2), np.float32), 2 * np.ones((3,), np.float32)])
            w = c.pull()
            np.testing.assert_allclose(w[0], np.ones((2, 2)))
            np.testing.assert_allclose(w[1], 2 * np.ones((3,)))
        assert ps.num_updates == 1
    finally:
        ps.stop()


def test_native_initial_weights_preserved():
    init = [np.full((2, 2), 3.0, np.float32), np.arange(3, dtype=np.float32)]
    ps = NativeParameterServer(init, mode=MODE_DELTA)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=init) as c:
            w = c.pull()
            np.testing.assert_allclose(w[0], init[0])
            np.testing.assert_allclose(w[1], init[1])
    finally:
        ps.stop()


def test_native_adag_scaling():
    ps = NativeParameterServer(_weights(), mode=MODE_ADAG, num_workers=4)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights()) as c:
            c.commit([np.full((2, 2), 4.0, np.float32), np.full((3,), 8.0, np.float32)])
            w = c.pull()
            np.testing.assert_allclose(w[0], np.ones((2, 2)))
            np.testing.assert_allclose(w[1], 2 * np.ones((3,)))
    finally:
        ps.stop()


def test_native_dynsgd_staleness():
    ps = NativeParameterServer(_weights(), mode=MODE_DYNSGD)
    ps.start()
    try:
        a = PSClient("127.0.0.1", ps.port, templates=_weights())
        b = PSClient("127.0.0.1", ps.port, templates=_weights())
        a.pull()
        b.pull()
        one = [np.ones((2, 2), np.float32), np.ones((3,), np.float32)]
        a.commit(one)  # staleness 0 -> full
        b.commit(one)  # staleness 1 -> half
        w = a.pull()
        np.testing.assert_allclose(w[0], np.full((2, 2), 1.5))
        a.close()
        b.close()
    finally:
        ps.stop()


def test_native_concurrent_commits_all_land():
    ps = NativeParameterServer([np.zeros((64,), np.float32)], mode=MODE_DELTA)
    ps.start()
    n_workers, n_commits = 8, 50

    def work(i):
        with PSClient("127.0.0.1", ps.port, templates=[np.zeros((64,), np.float32)]) as c:
            for _ in range(n_commits):
                c.pull()
                c.commit([np.ones((64,), np.float32)])

    try:
        threads = [threading.Thread(target=work, args=(i,)) for i in range(n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        np.testing.assert_allclose(ps.get_weights()[0], np.full((64,), n_workers * n_commits))
        assert ps.num_updates == n_workers * n_commits
    finally:
        ps.stop()


def test_native_async_downpour_trains(toy_dataset):
    from distkeras_tpu import AsyncDOWNPOUR
    from distkeras_tpu.data.transformers import LabelIndexTransformer
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.predictors import ModelPredictor

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2}, input_shape=(8,))
    trainer = AsyncDOWNPOUR(Model.init(spec, seed=0), loss="categorical_crossentropy",
                            batch_size=16, num_epoch=2, num_workers=4,
                            communication_window=4, learning_rate=0.05, native_ps=True)
    model = trainer.train(toy_dataset)
    assert trainer.parameter_server.num_updates > 0
    ds = ModelPredictor(model, features_col="features").predict(toy_dataset)
    ds = LabelIndexTransformer().transform(ds)
    acc = AccuracyEvaluator(prediction_col="prediction_index", label_col="label_index").evaluate(ds)
    assert acc > 0.9, f"native AsyncDOWNPOUR accuracy {acc}"


def test_native_int8_commits_match_python_hub():
    """The C++ hub must dequantize action-Q commits exactly like the
    Python hub: drive BOTH hubs with the same compressed client traffic
    and compare centers element-for-element."""
    from distkeras_tpu.runtime.parameter_server import ADAGParameterServer

    rng = np.random.default_rng(5)
    deltas = [[rng.normal(size=(2, 2)).astype(np.float32),
               rng.normal(size=(3,)).astype(np.float32)] for _ in range(4)]

    def drive(ps):
        ps.start()
        try:
            with PSClient("127.0.0.1", ps.port, templates=_weights(),
                          compress="int8") as c:
                for d in deltas:
                    c.commit(d)
                return c.pull()
        finally:
            ps.stop()

    w_native = drive(NativeParameterServer(_weights(), mode=MODE_ADAG,
                                           num_workers=4))
    w_python = drive(ADAGParameterServer(_weights(), num_workers=4))
    # same client stream (error feedback included) -> identical wire
    # bytes -> both hubs apply float(q)*scale/num_workers: bit-equal
    for n, p in zip(w_native, w_python):
        np.testing.assert_array_equal(n, p)


def test_native_pull_commit_direct_matches_python_hub():
    """The C++ hub's inproc pair (dk_ps_pull/dk_ps_commit) must move the
    center exactly like the Python hub's pull_direct/commit_direct —
    same deltas, same clocks, bit-equal centers."""
    from distkeras_tpu.runtime.parameter_server import DynSGDParameterServer

    rng = np.random.default_rng(7)
    deltas = [[rng.normal(size=(2, 2)).astype(np.float32),
               rng.normal(size=(3,)).astype(np.float32)] for _ in range(5)]

    def drive(ps):
        weights, clock = ps.pull_direct()
        assert clock == 0
        for i, d in enumerate(deltas):
            # commit against a deliberately stale clock every other step so
            # the DynSGD scaling path is exercised through both hubs
            ps.commit_direct(d, clock if i % 2 == 0 else max(clock - 1, 0))
            weights, clock = ps.pull_direct()
        assert clock == len(deltas) == ps.num_updates
        return weights

    w_native = drive(NativeParameterServer(_weights(), mode=MODE_DYNSGD))
    w_python = drive(DynSGDParameterServer(_weights()))
    for n, p in zip(w_native, w_python):
        np.testing.assert_array_equal(n, p)


def test_native_inproc_trainer_matches_python_inproc(toy_dataset):
    """transport='inproc' against the C++ hub: same trajectory as the
    Python hub inproc run (single worker, deterministic schedule)."""
    import jax

    from distkeras_tpu import AsyncDOWNPOUR
    from distkeras_tpu.models.base import Model, ModelSpec

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))

    def run(native):
        tr = AsyncDOWNPOUR(Model.init(spec, seed=0),
                           loss="categorical_crossentropy", batch_size=16,
                           num_epoch=1, num_workers=1, communication_window=4,
                           learning_rate=0.05, seed=0, transport="inproc",
                           native_ps=native)
        model = tr.train(toy_dataset)
        return tr, model

    t_n, m_n = run(True)
    t_p, m_p = run(False)
    assert t_n.history == t_p.history
    for a, b in zip(jax.tree.leaves(m_n.params), jax.tree.leaves(m_p.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_native_async_downpour_trains_with_int8_commits(toy_dataset):
    """End-to-end: the C++ hub + int8 commits still train the toy task."""
    import distkeras_tpu as dk
    from distkeras_tpu.data.transformers import LabelIndexTransformer
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.predictors import ModelPredictor

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    trainer = dk.AsyncDOWNPOUR(
        Model.init(spec, seed=0), loss="categorical_crossentropy",
        batch_size=16, num_epoch=2, num_workers=4, communication_window=4,
        learning_rate=0.05, seed=0, native_ps=True, compress_commits="int8")
    model = trainer.train(toy_dataset)
    ds = ModelPredictor(model, features_col="features").predict(toy_dataset)
    ds = LabelIndexTransformer().transform(ds)
    acc = AccuracyEvaluator(prediction_col="prediction_index",
                            label_col="label_index").evaluate(ds)
    assert acc > 0.9, f"native int8-commit training underperformed: {acc}"


# -- ISSUE 11: feature parity (sparse, adaptive, replication, M/G/Y) -----------

def _sparse_weights():
    return [np.zeros((6, 3), np.float32), np.zeros((4,), np.float32)]


def _native(weights=None, **kw):
    return NativeParameterServer(weights if weights is not None
                                 else _sparse_weights(), **kw)


def test_native_sparse_pull_commit_matches_python_hub():
    """S/V/U exchange against both hubs with identical client traffic:
    partial-touch row pulls and commits land bit-identical centers."""
    from distkeras_tpu.runtime.parameter_server import DeltaParameterServer

    rng = np.random.default_rng(3)
    ids_seq = [np.array([0, 2, 5], np.int64), np.array([1, 2], np.int64),
               np.array([3], np.int64)]

    def drive(ps):
        ps.start()
        try:
            with PSClient("127.0.0.1", ps.port,
                          templates=_sparse_weights(),
                          sparse_leaves=[0]) as c:
                c.pull()  # full seed
                for ids in ids_seq:
                    c.pull_nowait(sparse_rows=[ids])
                    c.wait_weights()
                    delta = [np.zeros((6, 3), np.float32),
                             rng.normal(size=(4,)).astype(np.float32)]
                    delta[0][ids] = rng.normal(
                        size=(ids.size, 3)).astype(np.float32)
                    c.commit(delta, sparse_rows=[ids])
                c.drain()
                return c.pull()
        finally:
            ps.stop()

    rng = np.random.default_rng(3)
    w_native = drive(_native(mode=MODE_DELTA, sparse_leaves=[0]))
    rng = np.random.default_rng(3)
    w_python = drive(DeltaParameterServer(_sparse_weights(),
                                          sparse_leaves=[0]))
    for a, b in zip(w_native, w_python):
        np.testing.assert_array_equal(a, b)


def test_native_sparse_int8_commit_matches_python_hub():
    """X (int8 row-block) commits dequantize identically on both hubs."""
    from distkeras_tpu.runtime.parameter_server import ADAGParameterServer

    ids = np.array([1, 4], np.int64)

    def drive(ps):
        ps.start()
        try:
            with PSClient("127.0.0.1", ps.port, templates=_sparse_weights(),
                          sparse_leaves=[0], compress="int8") as c:
                rng = np.random.default_rng(9)
                c.pull()
                for _ in range(3):
                    delta = [np.zeros((6, 3), np.float32),
                             rng.normal(size=(4,)).astype(np.float32)]
                    delta[0][ids] = rng.normal(size=(2, 3)).astype(np.float32)
                    c.commit(delta, sparse_rows=[ids])
                return c.pull()
        finally:
            ps.stop()

    w_native = drive(_native(mode=MODE_ADAG, num_workers=2,
                             sparse_leaves=[0]))
    w_python = drive(ADAGParameterServer(_sparse_weights(), num_workers=2,
                                         sparse_leaves=[0]))
    for a, b in zip(w_native, w_python):
        np.testing.assert_array_equal(a, b)


def test_native_sparse_rejects_bad_row_ids():
    """Out-of-bounds / unsorted id blobs drop the connection (the Python
    hub's ProtocolError semantics) and the hub survives for new peers."""
    ps = _native(mode=MODE_DELTA, sparse_leaves=[0])
    ps.start()
    try:
        from distkeras_tpu.runtime import networking as net

        for bad in (np.array([7], np.int64),      # out of range
                    np.array([3, 1], np.int64),   # unsorted
                    np.array([2, 2], np.int64)):  # duplicate
            sock = net.connect("127.0.0.1", ps.port)
            net.send_tensors(sock, net.ACTION_SPARSE_PULL, [bad])
            with pytest.raises((ConnectionError, ValueError)):
                net.recv_tensors(sock)
            sock.close()
        # hub still serves a well-formed peer
        with PSClient("127.0.0.1", ps.port, templates=_sparse_weights(),
                      sparse_leaves=[0]) as c:
            c.pull()
    finally:
        ps.stop()


def test_native_sparse_telemetry_established_names():
    """sync_telemetry surfaces sparse counters under the SAME names the
    Python hub emits (ps.sparse_rows_pulled / _committed / wire saved)."""
    from distkeras_tpu import observability as obs

    ps = _native(mode=MODE_DELTA, sparse_leaves=[0])
    ps.start()
    obs.enable()
    obs.reset()
    try:
        ids = np.array([0, 3], np.int64)
        with PSClient("127.0.0.1", ps.port, templates=_sparse_weights(),
                      sparse_leaves=[0]) as c:
            c.pull()
            c.pull_nowait(sparse_rows=[ids])
            c.wait_weights()
            delta = [np.zeros((6, 3), np.float32), np.ones((4,), np.float32)]
            delta[0][ids] = 1.0
            c.commit(delta, sparse_rows=[ids])
            c.drain()
        ps.sync_telemetry()
        counters = obs.snapshot()["counters"]
        assert counters.get("ps.sparse_rows_pulled") == 2.0
        assert counters.get("ps.sparse_rows_committed") == 2.0
        assert counters.get("ps.sparse_wire_bytes_saved", 0) > 0
    finally:
        obs.reset()
        obs.disable()
        ps.stop()


def test_native_adaptive_batch_of_one_bit_equal_plain():
    """Uncontended adaptive applies are bit-identical to adaptive=False —
    the C++ combiner's batch-of-one IS the plain apply (the Python hub's
    pinned property, extended to the native cell)."""
    def drive(adaptive):
        ps = _native(mode=MODE_DYNSGD, adaptive=adaptive)
        ps.start()
        try:
            rng = np.random.default_rng(11)
            with PSClient("127.0.0.1", ps.port,
                          templates=_sparse_weights()) as c:
                for i in range(6):
                    c.pull()
                    c.commit([rng.normal(size=(6, 3)).astype(np.float32),
                              rng.normal(size=(4,)).astype(np.float32)])
                return c.pull()
        finally:
            ps.stop()

    for a, b in zip(drive(True), drive(False)):
        np.testing.assert_array_equal(a, b)


def test_native_adaptive_concurrent_commits_merge_and_advance_clock():
    """Contended adaptive commits flow through the flat-combining merger:
    every commit lands (num_updates == commits), the clock advances by
    batch size, and merged batches are visible in stats."""
    ps = _native([np.zeros((64,), np.float32)], mode=MODE_DELTA,
                 adaptive=True)
    ps.start()
    n_workers, n_commits = 6, 30

    def work(_):
        with PSClient("127.0.0.1", ps.port,
                      templates=[np.zeros((64,), np.float32)]) as c:
            for _ in range(n_commits):
                c.pull()
                c.commit([np.zeros((64,), np.float32)])

    try:
        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        st = ps.stats()
        assert ps.num_updates == n_workers * n_commits
        assert st["clock"] == n_workers * n_commits
        assert st["commits"] == n_workers * n_commits
        assert 1 <= st["merge_batches"] <= n_workers * n_commits
        assert st["max_merge_batch"] >= 1
    finally:
        ps.stop()


def test_native_adaptive_rate_scale_applies():
    """A pushed per-worker rate scales that worker's commits in the C++
    apply path (the AdaptiveRateController -> dk_ps_set_rate_scale
    bridge), and an expired verdict reads as 1.0."""
    w = [np.zeros((4,), np.float32)]
    ps = _native(w, mode=MODE_DELTA, adaptive=True)
    ps.start()
    try:
        # worker 7 scaled to 0.5 for a generous hold
        ps._lib.dk_ps_set_rate_scale(ps._handle, 7, 0.5,
                                     ps.time_ns() + int(60e9))
        from distkeras_tpu.observability import distributed as dtrace

        ctx = dtrace.TraceContext(job_id="j", worker_id=7, span_id=1)
        with PSClient("127.0.0.1", ps.port, templates=w,
                      trace_context=ctx) as c:
            c.pull()
            c.commit([np.ones((4,), np.float32)])
        np.testing.assert_allclose(ps.get_weights()[0], np.full((4,), 0.5))
        # expired verdict: back to 1.0
        ps._lib.dk_ps_set_rate_scale(ps._handle, 7, 0.25, ps.time_ns() - 1)
        with PSClient("127.0.0.1", ps.port, templates=w,
                      trace_context=ctx) as c:
            c.pull()
            c.commit([np.ones((4,), np.float32)])
        np.testing.assert_allclose(ps.get_weights()[0], np.full((4,), 1.5))
    finally:
        ps.stop()


def test_native_answers_reconnect_hello():
    """Every native hub answers G with a Y hint: 0 outside a storm (and
    always 0 on a non-adaptive hub); an adaptive hub in a live storm
    hands out increasing slots and admits announcers that already waited
    (waits_taken > 0)."""
    from distkeras_tpu.runtime import networking as net

    def hello(port, waits=0):
        sock = net.connect("127.0.0.1", port)
        try:
            net.send_frame(sock, net.encode_reconnect_payload(waits))
            action, blobs = net.recv_tensors(sock)
            assert action == net.ACTION_RETRY
            return net.decode_retry_payload(blobs)
        finally:
            sock.close()

    plain = _native(mode=MODE_DELTA)
    plain.start()
    try:
        assert hello(plain.port) == 0
    finally:
        plain.stop()

    ps = _native(mode=MODE_DELTA, adaptive=True)
    ps.start()
    try:
        # tight storm thresholds so three hellos arm shedding
        ps._lib.dk_ps_set_storm_params(ps._handle, 3, 5000, 3000, 50, 2000)
        hints = [hello(ps.port) for _ in range(5)]
        assert hints[0] == 0 and hints[1] == 0  # below the storm threshold
        nonzero = [h for h in hints if h > 0]
        assert nonzero, hints
        assert nonzero == sorted(nonzero)  # later slots, spread in time
        assert hello(ps.port, waits=1) == 0  # waited its slot: admitted
        assert ps.backpressure_hints == len(nonzero)
    finally:
        ps.stop()


def test_native_health_reports_fold_into_collector(fresh_health):
    """Action-M reports against the native hub land in the process
    HealthCollector via the wrapper's drain (wire health reporting is
    hub-implementation-agnostic)."""
    import time

    from distkeras_tpu.observability import health as health_mod

    ps = _native(mode=MODE_DELTA)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port,
                      templates=_sparse_weights()) as c:
            c.report_health({"worker": "3", "windows": 4,
                             "window_wall_ms": {"mean": 12.0, "last": 11.0,
                                                "count": 4},
                             "reconnects_total": 0})
            c.drain()
        deadline = time.time() + 5
        while time.time() < deadline:
            if "3" in health_mod.collector().workers():
                break
            time.sleep(0.05)
        assert "3" in health_mod.collector().workers()
    finally:
        ps.stop()


def test_native_plain_client_bytes_identical_vs_python_hub():
    """THE wire-compat pin (ISSUE 11): an un-upgraded client's byte
    stream against a native sparse+adaptive hub is identical to its
    stream against the Python hub, and contains no S/V/U/X frame."""
    from distkeras_tpu.runtime import networking as net
    from distkeras_tpu.runtime.parameter_server import DeltaParameterServer

    t = _sparse_weights()

    def session_bytes(port):
        with PSClient("127.0.0.1", port, templates=t) as c:
            class _Rec:
                def __init__(self, sock):
                    self._sock = sock
                    self.tx = bytearray()

                def sendall(self, data):
                    self.tx += bytes(data)
                    return self._sock.sendall(data)

                def __getattr__(self, name):
                    return getattr(self._sock, name)

            rec = _Rec(c.sock)
            c.sock = rec
            c.pull()
            c.commit([np.full_like(a, 0.5) for a in t])
            c.pull()
            c.drain()
        return bytes(rec.tx)

    python_hub = DeltaParameterServer(t, idle_timeout=None)
    python_hub.start()
    native_hub = _native(mode=MODE_DELTA, sparse_leaves=[0], adaptive=True)
    native_hub.start()
    try:
        base = session_bytes(python_hub.port)
        against_native = session_bytes(native_hub.port)
    finally:
        python_hub.stop()
        native_hub.stop()
    assert base == against_native
    i = 0
    while i < len(base):
        n = int.from_bytes(base[i:i + 8], "big")
        assert base[i + 8:i + 9] not in (net.ACTION_SPARSE_PULL,
                                         net.ACTION_SPARSE_WEIGHTS,
                                         net.ACTION_SPARSE_COMMIT,
                                         net.ACTION_SPARSE_QCOMMIT)
        i += 8 + n


# -- replication (native primary / native standby) -----------------------------

def _feed_pair(primary_native, standby_native):
    from distkeras_tpu.runtime.parameter_server import DeltaParameterServer

    t = _sparse_weights()
    if primary_native:
        prim = _native(mode=MODE_DELTA)
    else:
        prim = DeltaParameterServer(t, idle_timeout=None)
    prim.start()
    if standby_native:
        stand = _native(mode=MODE_DELTA,
                        replica_of=("127.0.0.1", prim.port))
    else:
        stand = DeltaParameterServer(t, idle_timeout=None,
                                     replica_of=("127.0.0.1", prim.port))
    stand.start()
    return prim, stand


@pytest.mark.parametrize("primary_native,standby_native", [
    (True, False),
    pytest.param(False, True, marks=pytest.mark.slow),
    pytest.param(True, True, marks=pytest.mark.slow),
])
def test_native_replication_centers_track(primary_native, standby_native):
    """Hub implementations mix freely across the R feed: the standby's
    center tracks the primary bit for bit after each acked commit."""
    import time

    prim, stand = _feed_pair(primary_native, standby_native)
    t = _sparse_weights()
    try:
        assert stand.wait_synced(timeout=10)
        rng = np.random.default_rng(0)
        with PSClient("127.0.0.1", prim.port, templates=t) as c:
            for _ in range(4):
                c.pull()
                c.commit([rng.normal(size=(6, 3)).astype(np.float32),
                          rng.normal(size=(4,)).astype(np.float32)])
        deadline = time.time() + 10
        while time.time() < deadline:
            if stand.num_updates >= 4:
                break
            time.sleep(0.05)
        for a, b in zip(prim.get_weights(), stand.get_weights()):
            np.testing.assert_array_equal(a, b)
    finally:
        stand.stop()
        prim.stop()


def test_native_standby_promotes_on_primary_death():
    """A native standby whose primary dies promotes itself behind the
    clock fence within its retry budget, then serves commits."""
    import time

    prim, stand = _feed_pair(primary_native=False, standby_native=True)
    t = _sparse_weights()
    try:
        assert stand.wait_synced(timeout=10)
        with PSClient("127.0.0.1", prim.port, templates=t) as c:
            c.pull()
            c.commit([np.ones((6, 3), np.float32), np.ones((4,), np.float32)])
        time.sleep(0.3)
        prim.kill()
        deadline = time.time() + 20
        while time.time() < deadline and not stand.promoted:
            time.sleep(0.1)
        assert stand.promoted
        assert not stand.is_standby()
        assert stand.promoted_at_clock is not None
        # promoted standby serves commits like any hub
        with PSClient("127.0.0.1", stand.port, templates=t) as c:
            c.pull()
            c.commit([np.ones((6, 3), np.float32), np.ones((4,), np.float32)])
        np.testing.assert_allclose(stand.get_weights()[1], np.full((4,), 2.0))
    finally:
        stand.stop()
        prim.stop()


def test_native_never_synced_standby_refuses_traffic():
    """Pulls and commits against a native standby that has never synced
    drop the connection (no job state to serve or take over) — and the
    inproc pair raises the Python hub's errors."""
    # primary address that never answers: a bound-but-unserved port
    import socket as socket_mod

    placeholder = socket_mod.socket()
    placeholder.bind(("127.0.0.1", 0))
    dead_port = placeholder.getsockname()[1]
    placeholder.close()
    stand = _native(mode=MODE_DELTA, replica_of=("127.0.0.1", dead_port))
    stand.start()
    t = _sparse_weights()
    try:
        with pytest.raises((ConnectionError, ValueError, OSError)):
            with PSClient("127.0.0.1", stand.port, templates=t) as c:
                c.pull()
        with pytest.raises(RuntimeError, match="never-synced"):
            stand.pull_direct()
        with pytest.raises(RuntimeError, match="never-synced"):
            stand.commit_direct([np.zeros((6, 3), np.float32),
                                 np.zeros((4,), np.float32)], 0)
    finally:
        stand.stop()


# -- zero-copy transport (ISSUE 18) --------------------------------------------

def test_native_shm_attach_center_matches_tcp(tmp_path):
    """A shm=True PSClient negotiates rings with the C++ hub ('Z' arm,
    dk_ps_shm_attach) and the resulting center is identical to the same
    session over plain TCP; ring files are unlinked after the attach."""
    import os

    results = {}
    for shm in (False, True):
        ps = NativeParameterServer(_weights(), mode=MODE_DELTA,
                                   shm_dir=str(tmp_path))
        ps.start()
        try:
            with PSClient("127.0.0.1", ps.port, templates=_weights(),
                          shm=shm) as c:
                assert c.transport == ("shm" if shm else "tcp")
                c.pull()
                for _ in range(3):
                    c.commit([np.full((2, 2), 0.25, np.float32),
                              np.full((3,), 0.5, np.float32)])
                results[shm] = [w.copy() for w in c.pull()]
            assert ps.num_updates == 3
        finally:
            ps.stop()
    for x, y in zip(results[False], results[True]):
        np.testing.assert_array_equal(x, y)
    assert [f for f in os.listdir(str(tmp_path))
            if f.startswith("ring-")] == []


def test_cross_language_ring_byte_identical(tmp_path):
    """THE cross-language ring pin: bytes written by the C++ ring
    implementation read back identically through the Python one and vice
    versa, including EOF propagation — the two layouts are one layout."""
    import ctypes

    from distkeras_tpu.runtime import native as native_mod
    from distkeras_tpu.runtime import networking as net

    lib = native_mod._load()
    payload = bytes(range(256)) * 5  # 1280 B: wraps a 4 KiB ring

    # C++ producer -> Python consumer
    cpp_path = str(tmp_path / "cpp-ring").encode("utf-8")
    handle = lib.dk_shm_ring_create(cpp_path, 1, 4096)
    assert handle
    py_cons = net.ShmFrameRing.open(cpp_path.decode("utf-8"), "consumer")
    got = bytearray()
    buf = bytearray(512)
    for _ in range(4):
        assert lib.dk_shm_ring_write(handle, payload, len(payload),
                                     2000) == len(payload)
        want = len(got) + len(payload)
        while len(got) < want:
            n = py_cons.read_into(memoryview(buf), timeout=2.0)
            assert n > 0
            got += buf[:n]
    assert bytes(got) == payload * 4
    lib.dk_shm_ring_close(handle)  # producer EOF
    assert py_cons.read_into(memoryview(buf), timeout=2.0) == 0
    lib.dk_shm_ring_destroy(handle)
    py_cons.close()

    # Python producer -> C++ consumer
    py_path = str(tmp_path / "py-ring")
    py_prod = net.ShmFrameRing.create(py_path, "producer", capacity=4096)
    chandle = lib.dk_shm_ring_open(py_path.encode("utf-8"), 0)
    assert chandle
    writer = threading.Thread(
        target=lambda: [py_prod.write(payload, timeout=2.0)
                        for _ in range(4)] and None)
    writer.start()
    got2 = bytearray()
    cbuf = ctypes.create_string_buffer(512)
    while len(got2) < 4 * len(payload):
        n = lib.dk_shm_ring_read(chandle, cbuf, 512, 2000)
        assert n > 0
        got2 += cbuf.raw[:n]
    writer.join()
    assert bytes(got2) == payload * 4
    py_prod.close()  # EOF crosses the language boundary too
    assert lib.dk_shm_ring_read(chandle, cbuf, 512, 2000) == 0
    lib.dk_shm_ring_destroy(chandle)


# -- guidance + hygiene --------------------------------------------------------

def test_sparse_direct_pair_served_by_native_hub():
    """The FORMER last NotImplementedError combination (sparse +
    inproc + native) is served since ISSUE 15: the C++ hub's
    dk_ps_pull_sparse/dk_ps_commit_sparse round-trip row values with the
    Python hub's exact semantics, and the old guidance raises are gone."""
    ps = _native(mode=MODE_DELTA, sparse_leaves=[0])
    ps.start()
    try:
        ids = np.array([1, 4], np.int64)
        values, clock = ps.pull_sparse_direct([ids])
        assert values[0].shape == (2, 3)
        assert values[1].shape == (4,)
        grads = np.full((2, 3), 0.5, np.float32)
        ps.commit_sparse_direct([(ids, grads), np.zeros(4, np.float32)],
                                clock)
        v2, c2 = ps.pull_sparse_direct([ids])
        assert c2 == clock + 1
        np.testing.assert_array_equal(v2[0], values[0] + grads)
        # validation parity with the Python hub: bad ids are a loud
        # ValueError on BOTH directions, never a silent skip
        with pytest.raises(ValueError):
            ps.pull_sparse_direct([np.array([4, 1], np.int64)])
        with pytest.raises(ValueError):
            ps.commit_sparse_direct(
                [(np.array([99], np.int64), np.zeros((1, 3), np.float32)),
                 np.zeros(4, np.float32)], c2)
    finally:
        ps.stop()


def test_trainer_accepts_every_native_sparse_cell(toy_dataset):
    """The five Async* trainers accept EVERY native feature combination
    — the sparse+inproc guard is gone (ISSUE 15)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    spec = ModelSpec(name="mlp",
                     config={"hidden_sizes": (8,), "num_outputs": 2},
                     input_shape=(8,))
    # allowed: adaptive, health reporting, sparse over sockets, replica_of
    dk.AsyncADAG(Model.init(spec, seed=0), loss="categorical_crossentropy",
                 native_ps=True, adaptive=True, health_interval_s=1.0,
                 sparse_tables=(0,))
    dk.AsyncADAG(Model.init(spec, seed=0),
                 loss="categorical_crossentropy", native_ps=True,
                 transport="inproc", sparse_tables=(0,))


def test_native_build_is_warning_clean():
    """Build hygiene (ISSUE 11 satellite): the growing C++ surface must
    compile with -Wall -Wextra -Werror — a warning is a failed test, not
    line noise."""
    import os
    import subprocess
    import tempfile

    from conftest import require_tool

    require_tool("g++")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    from distkeras_tpu.runtime.native import BUILD_FLAGS

    with tempfile.TemporaryDirectory() as td:
        for src in ("ps_server.cpp", "data_loader.cpp"):
            proc = subprocess.run(
                ["g++"] + BUILD_FLAGS + ["-Wall", "-Wextra", "-Werror",
                 os.path.join(root, "native", src),
                 "-o", os.path.join(td, src + ".so")],
                capture_output=True, text=True, timeout=300)
            assert proc.returncode == 0, f"{src}:\n{proc.stderr}"
