"""Native (C++) parameter-server hub tests: the Python PSClient drives the
C++ server over the shared wire protocol, and results must match the
pure-Python hub bit-for-bit on deterministic schedules."""

import threading

import numpy as np
import pytest

from distkeras_tpu.runtime.native import (
    MODE_ADAG,
    MODE_DELTA,
    MODE_DYNSGD,
    NativeParameterServer,
    build_error,
    native_available,
)
from distkeras_tpu.runtime.parameter_server import PSClient

pytestmark = pytest.mark.skipif(
    not native_available(), reason=f"native PS unavailable: {build_error()}")


def _weights():
    return [np.zeros((2, 2), np.float32), np.zeros((3,), np.float32)]


def test_native_pull_commit_roundtrip():
    ps = NativeParameterServer(_weights(), mode=MODE_DELTA)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights()) as c:
            w = c.pull()
            assert all(np.all(x == 0) for x in w)
            c.commit([np.ones((2, 2), np.float32), 2 * np.ones((3,), np.float32)])
            w = c.pull()
            np.testing.assert_allclose(w[0], np.ones((2, 2)))
            np.testing.assert_allclose(w[1], 2 * np.ones((3,)))
        assert ps.num_updates == 1
    finally:
        ps.stop()


def test_native_initial_weights_preserved():
    init = [np.full((2, 2), 3.0, np.float32), np.arange(3, dtype=np.float32)]
    ps = NativeParameterServer(init, mode=MODE_DELTA)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=init) as c:
            w = c.pull()
            np.testing.assert_allclose(w[0], init[0])
            np.testing.assert_allclose(w[1], init[1])
    finally:
        ps.stop()


def test_native_adag_scaling():
    ps = NativeParameterServer(_weights(), mode=MODE_ADAG, num_workers=4)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights()) as c:
            c.commit([np.full((2, 2), 4.0, np.float32), np.full((3,), 8.0, np.float32)])
            w = c.pull()
            np.testing.assert_allclose(w[0], np.ones((2, 2)))
            np.testing.assert_allclose(w[1], 2 * np.ones((3,)))
    finally:
        ps.stop()


def test_native_dynsgd_staleness():
    ps = NativeParameterServer(_weights(), mode=MODE_DYNSGD)
    ps.start()
    try:
        a = PSClient("127.0.0.1", ps.port, templates=_weights())
        b = PSClient("127.0.0.1", ps.port, templates=_weights())
        a.pull()
        b.pull()
        one = [np.ones((2, 2), np.float32), np.ones((3,), np.float32)]
        a.commit(one)  # staleness 0 -> full
        b.commit(one)  # staleness 1 -> half
        w = a.pull()
        np.testing.assert_allclose(w[0], np.full((2, 2), 1.5))
        a.close()
        b.close()
    finally:
        ps.stop()


def test_native_concurrent_commits_all_land():
    ps = NativeParameterServer([np.zeros((64,), np.float32)], mode=MODE_DELTA)
    ps.start()
    n_workers, n_commits = 8, 50

    def work(i):
        with PSClient("127.0.0.1", ps.port, templates=[np.zeros((64,), np.float32)]) as c:
            for _ in range(n_commits):
                c.pull()
                c.commit([np.ones((64,), np.float32)])

    try:
        threads = [threading.Thread(target=work, args=(i,)) for i in range(n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        np.testing.assert_allclose(ps.get_weights()[0], np.full((64,), n_workers * n_commits))
        assert ps.num_updates == n_workers * n_commits
    finally:
        ps.stop()


def test_native_async_downpour_trains(toy_dataset):
    from distkeras_tpu import AsyncDOWNPOUR
    from distkeras_tpu.data.transformers import LabelIndexTransformer
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.predictors import ModelPredictor

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2}, input_shape=(8,))
    trainer = AsyncDOWNPOUR(Model.init(spec, seed=0), loss="categorical_crossentropy",
                            batch_size=16, num_epoch=2, num_workers=4,
                            communication_window=4, learning_rate=0.05, native_ps=True)
    model = trainer.train(toy_dataset)
    assert trainer.parameter_server.num_updates > 0
    ds = ModelPredictor(model, features_col="features").predict(toy_dataset)
    ds = LabelIndexTransformer().transform(ds)
    acc = AccuracyEvaluator(prediction_col="prediction_index", label_col="label_index").evaluate(ds)
    assert acc > 0.9, f"native AsyncDOWNPOUR accuracy {acc}"


def test_native_int8_commits_match_python_hub():
    """The C++ hub must dequantize action-Q commits exactly like the
    Python hub: drive BOTH hubs with the same compressed client traffic
    and compare centers element-for-element."""
    from distkeras_tpu.runtime.parameter_server import ADAGParameterServer

    rng = np.random.default_rng(5)
    deltas = [[rng.normal(size=(2, 2)).astype(np.float32),
               rng.normal(size=(3,)).astype(np.float32)] for _ in range(4)]

    def drive(ps):
        ps.start()
        try:
            with PSClient("127.0.0.1", ps.port, templates=_weights(),
                          compress="int8") as c:
                for d in deltas:
                    c.commit(d)
                return c.pull()
        finally:
            ps.stop()

    w_native = drive(NativeParameterServer(_weights(), mode=MODE_ADAG,
                                           num_workers=4))
    w_python = drive(ADAGParameterServer(_weights(), num_workers=4))
    # same client stream (error feedback included) -> identical wire
    # bytes -> both hubs apply float(q)*scale/num_workers: bit-equal
    for n, p in zip(w_native, w_python):
        np.testing.assert_array_equal(n, p)


def test_native_pull_commit_direct_matches_python_hub():
    """The C++ hub's inproc pair (dk_ps_pull/dk_ps_commit) must move the
    center exactly like the Python hub's pull_direct/commit_direct —
    same deltas, same clocks, bit-equal centers."""
    from distkeras_tpu.runtime.parameter_server import DynSGDParameterServer

    rng = np.random.default_rng(7)
    deltas = [[rng.normal(size=(2, 2)).astype(np.float32),
               rng.normal(size=(3,)).astype(np.float32)] for _ in range(5)]

    def drive(ps):
        weights, clock = ps.pull_direct()
        assert clock == 0
        for i, d in enumerate(deltas):
            # commit against a deliberately stale clock every other step so
            # the DynSGD scaling path is exercised through both hubs
            ps.commit_direct(d, clock if i % 2 == 0 else max(clock - 1, 0))
            weights, clock = ps.pull_direct()
        assert clock == len(deltas) == ps.num_updates
        return weights

    w_native = drive(NativeParameterServer(_weights(), mode=MODE_DYNSGD))
    w_python = drive(DynSGDParameterServer(_weights()))
    for n, p in zip(w_native, w_python):
        np.testing.assert_array_equal(n, p)


def test_native_inproc_trainer_matches_python_inproc(toy_dataset):
    """transport='inproc' against the C++ hub: same trajectory as the
    Python hub inproc run (single worker, deterministic schedule)."""
    import jax

    from distkeras_tpu import AsyncDOWNPOUR
    from distkeras_tpu.models.base import Model, ModelSpec

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))

    def run(native):
        tr = AsyncDOWNPOUR(Model.init(spec, seed=0),
                           loss="categorical_crossentropy", batch_size=16,
                           num_epoch=1, num_workers=1, communication_window=4,
                           learning_rate=0.05, seed=0, transport="inproc",
                           native_ps=native)
        model = tr.train(toy_dataset)
        return tr, model

    t_n, m_n = run(True)
    t_p, m_p = run(False)
    assert t_n.history == t_p.history
    for a, b in zip(jax.tree.leaves(m_n.params), jax.tree.leaves(m_p.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_native_async_downpour_trains_with_int8_commits(toy_dataset):
    """End-to-end: the C++ hub + int8 commits still train the toy task."""
    import distkeras_tpu as dk
    from distkeras_tpu.data.transformers import LabelIndexTransformer
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.predictors import ModelPredictor

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    trainer = dk.AsyncDOWNPOUR(
        Model.init(spec, seed=0), loss="categorical_crossentropy",
        batch_size=16, num_epoch=2, num_workers=4, communication_window=4,
        learning_rate=0.05, seed=0, native_ps=True, compress_commits="int8")
    model = trainer.train(toy_dataset)
    ds = ModelPredictor(model, features_col="features").predict(toy_dataset)
    ds = LabelIndexTransformer().transform(ds)
    acc = AccuracyEvaluator(prediction_col="prediction_index",
                            label_col="label_index").evaluate(ds)
    assert acc > 0.9, f"native int8-commit training underperformed: {acc}"
