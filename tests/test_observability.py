"""Unified telemetry subsystem (ISSUE #1): registry semantics, span
tracing, exporters, and the end-to-end async-trainer acceptance path.

The end-to-end test is the ISSUE's acceptance criterion verbatim: a
CPU-slice ``AsyncADAG`` run (2 workers, >=3 windows) must export a valid
Chrome trace (``json.loads``-able, ``ph``/``ts``/``dur`` events for window
and pull/commit spans) and a metrics snapshot with nonzero
``ps_commits_total``, ``ps_pull_bytes_total``, the per-window wall-vs-
device histograms, and the prefetch queue-depth gauge.
"""

import json
import threading
import time

import numpy as np
import pytest

from distkeras_tpu import observability as obs
from distkeras_tpu.observability import (
    DEFAULT_BUCKETS,
    JsonlFlusher,
    MetricsRegistry,
    SpanTracer,
)


@pytest.fixture
def telemetry():
    """Enable the process-global registry/tracer for one test, leaving a
    clean disabled slate afterwards (other tests must keep paying only the
    disabled-mode branch)."""
    obs.reset()
    obs.enable()
    yield obs
    obs.disable()
    obs.reset()


# -- registry semantics -------------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("commits_total")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)

    g = reg.gauge("depth")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3.0

    h = reg.histogram("lat_seconds")
    for v in (0.001, 0.01, 0.01, 5.0):
        h.observe(v)
    s = h.summary()
    assert s["count"] == 4 and s["min"] == 0.001 and s["max"] == 5.0
    assert s["sum"] == pytest.approx(5.021)
    # cumulative bucket counts are monotone and end at count
    cums = [c for _, c in s["buckets"]]
    assert cums == sorted(cums) and cums[-1] == 4


def test_histogram_boundary_value_lands_in_its_le_bucket():
    reg = MetricsRegistry(enabled=True)
    h = reg.histogram("h")
    h.observe(DEFAULT_BUCKETS[10])  # exactly a bound: le is inclusive
    assert [DEFAULT_BUCKETS[10], 1] in h.summary()["buckets"]


def test_labels_create_distinct_instruments():
    reg = MetricsRegistry(enabled=True)
    reg.gauge("stale", worker="0").set(1)
    reg.gauge("stale", worker="1").set(7)
    assert reg.value("stale", worker="0") == 1.0
    assert reg.value("stale", worker="1") == 7.0
    assert reg.value("stale", worker="2") is None  # value() never creates
    snap = reg.snapshot()
    assert snap["gauges"]['stale{worker="0"}'] == 1.0
    assert snap["gauges"]['stale{worker="1"}'] == 7.0


def test_kind_conflict_raises():
    reg = MetricsRegistry(enabled=True)
    reg.counter("x_total")
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("x_total")


def test_disabled_registry_is_noop():
    reg = MetricsRegistry(enabled=False)
    c, g, h = reg.counter("c"), reg.gauge("g"), reg.histogram("h")
    c.inc(5)
    g.set(9)
    h.observe(1.0)
    assert c.value == 0.0 and g.value == 0.0 and h.count == 0
    # flipping the switch makes the SAME cached instruments live
    reg.enabled = True
    c.inc(5)
    assert c.value == 5.0


def test_thread_safety_under_concurrent_writers():
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("n_total")
    h = reg.histogram("v")

    def writer(i):
        for k in range(1000):
            c.inc()
            h.observe(0.001 * (i + 1))

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000


def test_prometheus_rendering():
    reg = MetricsRegistry(enabled=True)
    reg.counter("pulls_total").inc(3)
    reg.gauge("stale", worker="0").set(2)
    reg.histogram("lat_seconds").observe(0.01)
    text = reg.render_prometheus()
    assert "# TYPE pulls_total counter" in text
    assert "pulls_total 3.0" in text
    assert '# TYPE stale gauge' in text and 'stale{worker="0"} 2.0' in text
    assert "# TYPE lat_seconds histogram" in text
    assert 'lat_seconds_bucket{le="+Inf"} 1' in text
    assert "lat_seconds_count 1" in text


# -- span tracer --------------------------------------------------------------

def test_span_nesting_records_depth_and_containment():
    tr = SpanTracer(capacity=64, enabled=True)
    with tr.span("outer", kind="epoch"):
        with tr.span("inner"):
            time.sleep(0.001)
    inner, outer = tr.events()  # inner exits (and records) first
    assert inner["name"] == "inner" and outer["name"] == "outer"
    assert inner["depth"] == 1 and outer["depth"] == 0
    assert inner["ts_us"] >= outer["ts_us"]
    assert inner["ts_us"] + inner["dur_us"] <= outer["ts_us"] + outer["dur_us"] + 1
    assert outer["attrs"] == {"kind": "epoch"}


def test_ring_buffer_eviction_keeps_newest_and_counts_drops():
    tr = SpanTracer(capacity=4, enabled=True)
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    assert len(tr) == 4
    assert [e["name"] for e in tr.events()] == ["s6", "s7", "s8", "s9"]
    assert tr.dropped == 6


def test_disabled_tracer_records_nothing():
    tr = SpanTracer(capacity=4, enabled=False)
    with tr.span("x"):
        pass
    assert len(tr) == 0


def test_chrome_trace_export_is_valid_trace_event_json(tmp_path):
    tr = SpanTracer(capacity=16, enabled=True)
    with tr.span("a", worker=0):
        pass
    path = tr.export_chrome(str(tmp_path / "trace.json"))
    with open(path) as f:
        parsed = json.loads(f.read())
    assert isinstance(parsed["traceEvents"], list) and parsed["traceEvents"]
    for ev in parsed["traceEvents"]:
        assert ev["ph"] == "X"
        assert isinstance(ev["ts"], int) and isinstance(ev["dur"], int)
        assert "pid" in ev and "tid" in ev and "name" in ev


def test_jsonl_export_and_drain(tmp_path):
    tr = SpanTracer(capacity=16, enabled=True)
    for name in ("a", "b"):
        with tr.span(name):
            pass
    lines = list(tr.jsonl())
    assert [json.loads(l)["name"] for l in lines] == ["a", "b"]
    drained = tr.drain()
    assert len(drained) == 2 and len(tr) == 0


def test_span_error_annotated():
    """A span that ends by raising records error=1 + the exception type
    (countable/filterable in trace viewers) instead of closing silently."""
    tr = SpanTracer(enabled=True)
    with pytest.raises(RuntimeError):
        with tr.span("boom"):
            raise RuntimeError("x")
    (ev,) = tr.events()
    assert ev["attrs"]["error"] == 1
    assert ev["attrs"]["error_type"] == "RuntimeError"


def test_jsonl_flusher_writes_selfcontained_lines(tmp_path):
    reg = MetricsRegistry(enabled=True)
    tr = SpanTracer(enabled=True)
    reg.counter("c_total").inc(2)
    with tr.span("s"):
        pass
    path = str(tmp_path / "telemetry.jsonl")
    flusher = JsonlFlusher(path, reg, tracer=tr, interval=60.0)
    flusher.start()
    flusher.stop()  # final flush
    with open(path) as f:
        lines = [json.loads(l) for l in f.read().splitlines()]
    assert lines, "stop() must land at least one flush"
    assert lines[0]["metrics"]["counters"]["c_total"] == 2.0
    assert [s["name"] for s in lines[0]["spans"]] == ["s"]
    # spans are drained: a second flush does not repeat them
    flusher.flush()
    with open(path) as f:
        lines = [json.loads(l) for l in f.read().splitlines()]
    assert "spans" not in lines[-1]


# -- instrumented layers ------------------------------------------------------

def test_prefetch_feed_gauges_and_chunk_latency(telemetry, toy_dataset):
    from distkeras_tpu.data.dataset import prefetch_to_device

    chunks = toy_dataset.chunked_epoch(16, ["features", "label"],
                                      window=1, chunk_windows=8)
    seen = 0
    for _ in prefetch_to_device(chunks, lambda ch: ch["features"].shape):
        seen += 1
    assert seen == 8
    snap = obs.snapshot()
    assert snap["counters"]["feed_chunks_total"] == 8.0
    assert "feed_queue_depth" in snap["gauges"]
    assert snap["histograms"]["feed_chunk_load_seconds"]["count"] == 8


def test_prefetch_raises_when_producer_dies_without_sentinel(monkeypatch):
    """ADVICE round 5: a producer killed without its 'done'/'error'
    sentinel must surface as an error, not a silent q.get() hang."""
    from distkeras_tpu.data.dataset import prefetch_to_device

    class DeadThread:
        def __init__(self, *a, **kw):
            pass

        def start(self):
            pass  # never runs: simulates death-before-first-put

        def is_alive(self):
            return False

    monkeypatch.setattr(threading, "Thread", DeadThread)
    it = prefetch_to_device(iter([{"x": 1}]), lambda ch: ch)
    with pytest.raises(RuntimeError, match="producer thread died"):
        next(it)


def test_head_recompute_factor_formula():
    from distkeras_tpu.parallel.pipeline import head_recompute_factor

    # round 6: the 1F1B head + CE runs in a lax.cond taken only on the
    # last rank's valid backward units — exactly M evaluations per step,
    # same as GPipe, so the factor is 1.0 at EVERY (pp, M).  The round-5
    # where-masked schedule measured pp * (1 + 2(pp-1)/M); if this
    # assertion ever needs a formula again, head recompute came back
    assert head_recompute_factor(1, 8) == 1.0
    assert head_recompute_factor(2, 8) == 1.0
    assert head_recompute_factor(4, 8) == 1.0
    with pytest.raises(ValueError):
        head_recompute_factor(0, 8)


def test_punchcard_telemetry_action(telemetry, tmp_path):
    from distkeras_tpu.runtime.job_deployment import Punchcard, fetch_telemetry

    obs.counter("ps_commits_total").inc(3)
    with obs.span("async.window", worker=0):
        pass
    obs.TRACER.record_span("ps.handle_commit", 1_000_000, 2_000_000,
                           worker=0, staleness=2)
    pc = Punchcard(secret="s3cret").start()
    try:
        resp = fetch_telemetry("127.0.0.1", pc.port, "s3cret",
                               trace=True, prometheus=True, fleet=True)
    finally:
        pc.stop()
    assert resp["enabled"] is True
    assert resp["metrics"]["counters"]["ps_commits_total"] == 3.0
    assert any(e["name"] == "async.window"
               for e in resp["trace"]["traceEvents"])
    assert "ps_commits_total 3.0" in resp["prometheus"]
    # the fleet_report rides the same action (issue 5): straggler ranking +
    # per-worker staleness attribution, computed daemon-side
    assert resp["fleet"]["total_commits"] == 1
    assert resp["fleet"]["commit_context_coverage"] == 1.0
    assert resp["fleet"]["workers"]["0"]["commits"] == 1


# -- end-to-end acceptance: AsyncADAG smoke run -------------------------------

def test_async_adag_smoke_exports_metrics_and_chrome_trace(telemetry, toy_dataset,
                                                           tmp_path):
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    trainer = dk.AsyncADAG(Model.init(spec, seed=0),
                           loss="categorical_crossentropy", batch_size=16,
                           num_epoch=1, num_workers=2, communication_window=4,
                           learning_rate=0.05, seed=0)
    trainer.train(toy_dataset)
    # 1024 rows / 2 workers / (16 batch * 4 window) = 8 windows per worker
    assert len(trainer.history) >= 3 * 2

    snap = obs.snapshot()
    assert snap["counters"]["ps_commits_total"] > 0
    assert snap["counters"]["ps_pull_bytes_total"] > 0
    assert snap["counters"]["ps_commit_bytes_total"] > 0
    # issue-3 client-side hot-path instruments (exported through the same
    # registry the telemetry punchcard action snapshots)
    assert snap["counters"]["ps.commit_bytes"] > 0
    assert snap["histograms"]["ps.pull_latency_ms"]["count"] > 0
    assert snap["histograms"]["ps.commit_latency_ms"]["count"] > 0
    assert snap["histograms"]["ps.serialize_ms"]["count"] > 0
    assert "ps.inflight_depth" in snap["gauges"]
    # hub-side staleness distribution: one observation per applied commit
    assert snap["histograms"]["ps_commit_staleness"]["count"] \
        == snap["counters"]["ps_commits_total"]
    wall = snap["histograms"]["async_window_wall_seconds"]
    dev = snap["histograms"]["async_window_device_seconds"]
    assert wall["count"] >= 3 and dev["count"] >= 3
    assert wall["sum"] >= dev["sum"]  # the wall leg contains the device leg
    assert any(k.startswith("ps_staleness{") for k in snap["gauges"])
    # the async worker feed rides the shared prefetch machinery under its
    # own metric prefix (so window staging cannot pollute the disk feed's
    # instruments), and the prefetch queue-depth gauge populates in an
    # async-only run too
    assert "async_feed_queue_depth" in snap["gauges"]
    assert snap["counters"]["async_feed_chunks_total"] > 0
    assert snap["counters"]['trainer_epochs_total{trainer="AsyncADAG"}'] == 1.0
    assert snap["histograms"]['trainer_window_loss{trainer="AsyncADAG"}']["count"] \
        == len(trainer.history)

    # the exported Chrome trace parses and carries complete (ph/ts/dur)
    # events for the window and pull/commit spans
    path = obs.TRACER.export_chrome(str(tmp_path / "smoke_trace.json"))
    with open(path) as f:
        parsed = json.loads(f.read())
    names = {e["name"] for e in parsed["traceEvents"]}
    assert {"async.window", "ps.pull", "ps.commit"} <= names
    for ev in parsed["traceEvents"]:
        assert ev["ph"] == "X" and "ts" in ev and "dur" in ev

    # the wall/device decomposition is coherent per window: device time
    # never exceeds wall time
    assert dev["max"] <= wall["max"] * 1.001


# -- prometheus exposition hardening (issue-5 satellites) ---------------------

def test_prometheus_label_value_escaping():
    """Backslash, double-quote and newline in label values are escaped per
    the text-format spec — unescaped they corrupt the whole scrape."""
    reg = MetricsRegistry(enabled=True)
    reg.counter("c_total", path='a\\b"c\nd').inc()
    text = reg.render_prometheus()
    assert 'c_total{path="a\\\\b\\"c\\nd"} 1.0' in text
    assert "\n\n" not in text  # the raw newline never leaked into a line


def test_prometheus_escape_helper_order():
    from distkeras_tpu.observability.sinks import escape_label_value

    # backslash escapes FIRST, or the quote/newline escapes double-escape
    assert escape_label_value('\\') == '\\\\'
    assert escape_label_value('"') == '\\"'
    assert escape_label_value('\n') == '\\n'
    assert escape_label_value('\\n') == '\\\\n'


def test_histogram_overflow_bucket_and_quantile_surface():
    """Values past the last fixed log bound land in the explicit +Inf
    overflow bucket, and the exposition carries the full cumulative bucket
    series plus _sum/_count — the shape histogram_quantile() needs."""
    reg = MetricsRegistry(enabled=True)
    h = reg.histogram("ps.pull_latency_ms")
    h.observe(0.5)
    h.observe(1e30)          # beyond every bound -> overflow
    h.observe(float("inf"))  # +inf -> overflow too
    h.observe(float("nan"))  # dropped: would poison sum/mean forever
    assert h.count == 3
    s = h.summary()
    assert ["+Inf", 3] in s["buckets"]
    text = reg.render_prometheus()
    assert 'ps_pull_latency_ms_bucket{le="+Inf"} 3' in text
    assert "ps_pull_latency_ms_count 3" in text
    assert "ps_pull_latency_ms_sum" in text
    # cumulative bucket series is monotone nondecreasing and ends at count
    cums = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
            if line.startswith("ps_pull_latency_ms_bucket")]
    assert cums == sorted(cums) and cums[-1] == 3


def test_histogram_observe_n_bulk_matches_loop():
    """observe_n(v, n) — the native hub's O(1)-per-slot staleness replay —
    must equal n individual observe(v) calls."""
    reg = MetricsRegistry(enabled=True)
    bulk, loop = reg.histogram("bulk"), reg.histogram("loop")
    for v, n in ((0.0, 3), (2.0, 5), (1e30, 2)):
        bulk.observe_n(v, n)
        for _ in range(n):
            loop.observe(v)
    bulk.observe_n(1.0, 0)              # n=0: no-op
    bulk.observe_n(float("nan"), 4)     # NaN: dropped, same as observe()
    sb, sl = bulk.summary(), loop.summary()
    assert sb == sl
    assert sb["count"] == 10 and sb["min"] == 0.0


# -- distributed tracing: context propagation (issue-5 tentpole) --------------

@pytest.fixture
def hub_and_templates():
    from distkeras_tpu.runtime.parameter_server import DeltaParameterServer

    templates = [np.zeros((4, 4), np.float32), np.zeros(3, np.float32)]
    ps = DeltaParameterServer(templates, port=0)
    ps.start()
    yield ps, templates
    ps.stop()


def _wait_spans(*names, timeout=5.0):
    """The hub acks INSIDE the handler span, so a client can unblock
    before the span records (the ack-before-telemetry-tail ordering,
    ISSUE 14's motivating shape) — poll briefly instead of racing."""
    import time as _time

    deadline = _time.monotonic() + timeout
    while _time.monotonic() < deadline:
        events = obs.TRACER.events()
        got = {n: [e for e in events if e["name"] == n] for n in names}
        if all(got.values()):
            return got
        _time.sleep(0.01)
    return got


def test_trace_context_announce_tags_hub_spans(telemetry, hub_and_templates):
    from distkeras_tpu.observability import distributed as dtrace
    from distkeras_tpu.runtime.parameter_server import PSClient

    ps, templates = hub_and_templates
    ctx = dtrace.TraceContext(job_id="j1", worker_id=4,
                              span_id=dtrace.new_span_id())
    with PSClient("127.0.0.1", ps.port, templates=templates,
                  trace_context=ctx) as client:
        pulled = client.pull()
        client.commit([np.ones_like(t) for t in pulled])
        # NTP-style offset on loopback against the same physical clock:
        # tiny, and within the sample's own error bound
        assert client.clock_error_ns is not None
        assert abs(client.clock_offset_ns) <= client.clock_error_ns + 5_000_000
    got = _wait_spans("ps.handle_commit", "ps.handle_pull")
    commits, pulls = got["ps.handle_commit"], got["ps.handle_pull"]
    assert commits and pulls
    assert commits[0]["attrs"]["worker"] == 4
    assert commits[0]["attrs"]["job"] == "j1"
    assert commits[0]["attrs"]["staleness"] == 0
    assert pulls[0]["attrs"]["worker"] == 4


def test_unannounced_client_wire_unchanged(telemetry, hub_and_templates):
    """No trace_context => no T frame: the byte stream is the pre-T
    protocol exactly, and hub commit spans simply carry no worker."""
    from distkeras_tpu.runtime.parameter_server import PSClient

    ps, templates = hub_and_templates
    with PSClient("127.0.0.1", ps.port, templates=templates) as client:
        client.commit([np.ones_like(t) for t in templates])
    (commit,) = _wait_spans("ps.handle_commit")["ps.handle_commit"]
    assert "worker" not in commit["attrs"]


def test_inproc_commit_span_reads_thread_context(telemetry, hub_and_templates):
    from distkeras_tpu.observability import distributed as dtrace
    from distkeras_tpu.runtime.parameter_server import InprocPSClient

    ps, templates = hub_and_templates
    ctx = dtrace.TraceContext(job_id="j2", worker_id=7,
                              span_id=dtrace.new_span_id())
    dtrace.activate(ctx)
    try:
        client = InprocPSClient(ps, templates=templates, trace_context=ctx)
        client.pull()
        client.commit([np.ones_like(t) for t in templates])
    finally:
        dtrace.deactivate()
    (commit,) = [e for e in obs.TRACER.events()
                 if e["name"] == "ps.handle_commit"]
    assert commit["attrs"]["worker"] == 7
    assert commit["attrs"]["transport"] == "inproc"


def test_native_hub_stats_surface_python_registry_names(telemetry):
    from distkeras_tpu.observability import distributed as dtrace
    from distkeras_tpu.runtime import native
    from distkeras_tpu.runtime.parameter_server import PSClient

    if not native.native_available():
        pytest.skip(f"native hub unavailable: {native.build_error()}")
    templates = [np.zeros((4, 4), np.float32), np.zeros(3, np.float32)]
    ps = native.NativeParameterServer(templates, mode=native.MODE_DELTA)
    ps.start()
    try:
        ctx = dtrace.TraceContext(job_id="jn", worker_id=1,
                                  span_id=dtrace.new_span_id())
        with PSClient("127.0.0.1", ps.port, templates=templates,
                      trace_context=ctx) as client:
            pulled = client.pull()
            client.commit([np.ones_like(t) for t in pulled])
            client.commit([np.ones_like(t) for t in pulled])
        # inproc twin with thread-local context
        dtrace.activate(dtrace.TraceContext(job_id="jn", worker_id=5,
                                            span_id=dtrace.new_span_id()))
        try:
            weights, clock = ps.pull_direct()
            ps.commit_direct([np.ones_like(w) for w in weights], clock)
        finally:
            dtrace.deactivate()
        ps.sync_telemetry()
    finally:
        ps.stop()
    snap = obs.snapshot()
    # the SAME names the Python hub emits — hub-implementation-agnostic
    assert snap["counters"]["ps_commits_total"] == 3.0
    assert snap["counters"]["ps_pulls_total"] >= 2.0
    assert snap["counters"]["ps_commit_bytes_total"] > 0
    assert snap["counters"]["ps_pull_bytes_total"] > 0
    assert snap["histograms"]["ps_commit_staleness"]["count"] == 3
    assert "ps_live_workers" in snap["gauges"]
    # the drained commit log became attributable hub spans
    commits = [e for e in obs.TRACER.events() if e["name"] == "ps.handle_commit"]
    workers = sorted(e["attrs"].get("worker") for e in commits)
    assert workers == [1, 1, 5]
    assert all(e["attrs"]["hub"] == "native" for e in commits)
    # a second sync advances by deltas only (no double counting)
    obs.reset()
    ps.sync_telemetry()
    assert obs.snapshot()["counters"].get("ps_commits_total", 0.0) == 0.0


# -- distributed tracing: clock-aligned merge ---------------------------------

def test_merge_traces_two_subprocess_workers(telemetry, tmp_path):
    """The acceptance-shaped multi-process merge: a hub in THIS process
    (the clock reference) + two real subprocess workers, each announcing a
    context and flushing its own offset-stamped JSONL.  The merged Chrome
    trace must be monotonic per (pid, tid) track and each child's offset
    estimate must sit within its own documented error bound (same physical
    clock => true offset ~ 0)."""
    import subprocess
    import sys

    from distkeras_tpu.observability import distributed as dtrace
    from distkeras_tpu.runtime.parameter_server import DeltaParameterServer

    templates = [np.zeros((4, 4), np.float32), np.zeros(3, np.float32)]
    ps = DeltaParameterServer(templates, port=0)
    ps.start()
    trace_dir = str(tmp_path / "traces")
    try:
        import os

        child = os.path.join(os.path.dirname(__file__),
                             "multihost_child_trace.py")
        env = dict(os.environ)
        env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(child))
                             + os.pathsep + env.get("PYTHONPATH", ""))
        procs = [subprocess.run(
            [sys.executable, child, str(ps.port), str(w), trace_dir],
            capture_output=True, text=True, timeout=120, env=env)
            for w in (0, 1)]
        for p in procs:
            assert p.returncode == 0, f"child failed:\n{p.stdout}\n{p.stderr}"
    finally:
        ps.stop()
    # the hub process flushes too (offset 0: it IS the reference)
    dtrace.flush_process_trace(trace_dir, job_id="mergejob", role="hub")

    metas, spans = dtrace.load_trace_dir(trace_dir)
    assert len(metas) == 3  # hub + 2 workers
    for m in metas:
        if m["role"] == "worker":
            # alignment-error contract: |estimated offset| <= its error
            # bound (+ scheduling slack) on a shared physical clock
            assert m["clock_error_ns"] is not None
            assert abs(m["clock_offset_ns"]) <= m["clock_error_ns"] + 20_000_000
            assert m["clock_error_ns"] < 1_000_000_000

    merged = dtrace.merge_traces(trace_dir)
    events = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    assert merged["otherData"]["processes"] == 3
    assert merged["otherData"]["spans"] == len(events)
    assert merged["otherData"]["alignment_error_us"] >= 0
    # every child's windows and the hub's attributed commit handling made it
    names = {e["name"] for e in events}
    assert {"async.window", "ps.handle_commit", "ps.handle_pull"} <= names
    commit_workers = {e["args"].get("worker") for e in events
                      if e["name"] == "ps.handle_commit"}
    assert {0, 1} <= commit_workers
    # monotonic per (pid, tid) track after the merge sort
    by_track = {}
    for e in events:
        by_track.setdefault((e["pid"], e["tid"]), []).append(e["ts"])
    for track, ts in by_track.items():
        assert ts == sorted(ts), f"track {track} not monotonic"
    # and it round-trips through json for chrome://tracing
    path = dtrace.export_merged(trace_dir, str(tmp_path / "merged.json"))
    with open(path) as f:
        assert json.loads(f.read())["traceEvents"]


# -- distributed tracing: straggler + staleness attribution -------------------

def test_fleet_report_chaosproxy_delay_names_top_straggler(telemetry):
    """The acceptance criterion's delay leg: two workers against one hub,
    one of them routed through a ChaosProxy that delays every frame —
    fleet_report must rank the delayed worker as the top straggler."""
    from distkeras_tpu.observability import distributed as dtrace
    from distkeras_tpu.runtime.faults import DELAY, ChaosProxy, Fault, FaultPlan
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    templates = [np.zeros((8, 8), np.float32)]
    ps = DeltaParameterServer(templates, port=0)
    ps.start()
    plan = FaultPlan([Fault(conn=0, direction="s2c", frame=k, kind=DELAY,
                            delay_s=0.02) for k in range(32)])
    proxy = ChaosProxy("127.0.0.1", ps.port, plan=plan)
    proxy.start()
    try:
        def run_worker(idx, port):
            ctx = dtrace.TraceContext(job_id="chaos", worker_id=idx,
                                      span_id=dtrace.new_span_id())
            with PSClient("127.0.0.1", port, templates=templates,
                          trace_context=ctx) as client:
                for w in range(4):
                    with obs.span("async.window", worker=idx, window=w):
                        pulled = client.pull()
                        client.commit([np.full_like(t, 0.1) for t in pulled])

        run_worker(0, ps.port)      # direct: fast
        run_worker(1, proxy.port)   # proxied: every frame held 20 ms
    finally:
        proxy.stop()
        ps.stop()
    report = dtrace.fleet_report()
    assert report["top_straggler"] == "1"
    w0, w1 = report["workers"]["0"], report["workers"]["1"]
    assert w1["mean_window_ms"] > w0["mean_window_ms"]
    assert w0["windows"] == w1["windows"] == 4
    # every hub commit span carried a context (coverage = 1.0)
    assert report["commit_context_coverage"] == 1.0
    # staleness is attributed per worker (present, non-negative)
    assert w0["mean_staleness"] is not None and w0["mean_staleness"] >= 0


def test_fleet_report_flags_reconnect_storms(telemetry):
    from distkeras_tpu.observability import distributed as dtrace

    t0 = 1_000_000_000
    for k in range(3):
        obs.TRACER.record_span("ps.reconnect", t0 + k, t0 + k + 1000, worker=2)
    obs.TRACER.record_span("ps.reconnect", t0, t0 + 1000, worker=0)
    report = dtrace.fleet_report()
    assert report["reconnect_storms"] == ["2"]
    assert report["workers"]["2"]["reconnects"] == 3
    assert report["workers"]["0"]["reconnects"] == 1


# -- end-to-end acceptance: AsyncADAG over the transport x hub matrix ---------

@pytest.mark.parametrize("transport,native_ps", [
    ("socket", False),
    ("inproc", False),
    ("socket", True),
    ("inproc", True),
])
def test_e2e_async_adag_commit_context_coverage(telemetry, toy_dataset,
                                                tmp_path, monkeypatch,
                                                transport, native_ps):
    """The issue-5 acceptance run: an AsyncADAG job on each transport/hub
    combination produces a merged Chrome trace in which >=95% of hub
    commit spans carry a worker trace context."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.observability import distributed as dtrace

    if native_ps:
        from distkeras_tpu.runtime import native

        if not native.native_available():
            pytest.skip(f"native hub unavailable: {native.build_error()}")
    trace_dir = str(tmp_path / "traces")
    monkeypatch.setenv("DKT_TRACE_DIR", trace_dir)
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    trainer = dk.AsyncADAG(Model.init(spec, seed=0),
                           loss="categorical_crossentropy", batch_size=16,
                           num_epoch=1, num_workers=2, communication_window=4,
                           learning_rate=0.05, seed=0, transport=transport,
                           native_ps=native_ps, trace_context="e2ejob")
    trainer.train(toy_dataset)

    report = dtrace.fleet_report(trace_dir=trace_dir)
    assert report["total_commits"] > 0
    assert report["commit_context_coverage"] >= 0.95
    # both workers show up as attributed committers AND window owners
    assert {"0", "1"} <= set(report["workers"])
    assert all(report["workers"][w]["windows"] > 0 for w in ("0", "1"))
    merged = dtrace.merge_traces(trace_dir)
    names = {e["name"] for e in merged["traceEvents"] if e.get("ph") == "X"}
    assert {"async.window", "ps.handle_commit"} <= names


# -- CI/tooling guards (issue-5 satellites) -----------------------------------

def test_observability_imports_are_cycle_free_and_jax_free():
    """The observability package (distributed tracing included) must import
    standalone — no cycles, no jax/numpy/runtime pulled in — so the
    punchcard daemon and bare tooling can use it without a backend."""
    import subprocess
    import sys

    code = (
        "import sys\n"
        "import distkeras_tpu.observability.distributed\n"
        "import distkeras_tpu.observability.metrics\n"
        "import distkeras_tpu.observability.sinks\n"
        "import distkeras_tpu.observability.tracing\n"
        "from distkeras_tpu import observability\n"
        "observability.TraceContext  # lazy export resolves\n"
        "assert 'jax' not in sys.modules, 'observability dragged jax in'\n"
        "assert 'numpy' not in sys.modules, 'observability dragged numpy in'\n"
        "assert 'distkeras_tpu.runtime' not in sys.modules, 'import cycle'\n"
        "print('CLEAN')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "CLEAN" in proc.stdout


def test_disabled_telemetry_hot_path_makes_zero_registry_calls(monkeypatch):
    """Overhead guard: with telemetry disabled, a full pull/commit exchange
    (client and hub hot paths) performs ZERO registry lookups and records
    zero spans — the disabled cost is one branch, not a dict get."""
    from distkeras_tpu.observability.metrics import MetricsRegistry
    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer,
        PSClient,
    )

    obs.disable()
    obs.reset()
    calls = []
    orig_get = MetricsRegistry._get

    def counting_get(self, kind, name, labels):
        calls.append((kind, name))
        return orig_get(self, kind, name, labels)

    monkeypatch.setattr(MetricsRegistry, "_get", counting_get)
    templates = [np.zeros((4, 4), np.float32)]
    ps = DeltaParameterServer(templates, port=0)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=templates) as client:
            for _ in range(3):
                pulled = client.pull()
                client.commit([np.ones_like(t) for t in pulled])
    finally:
        ps.stop()
    assert calls == [], f"registry touched while disabled: {calls[:5]}"
    assert len(obs.TRACER.events()) == 0


@pytest.mark.parametrize("package", ["observability", "runtime", ".", "tests",
                                     "data", "parallel", "models", "ops",
                                     "examples", "bench", "analysis"])
def test_package_is_lint_clean(package):
    """Satellite (PR 5, extended package-by-package through PR 10, and
    consolidated by PR 12): ruff-clean check scoped to the instrumented
    packages.  The implementation now lives in ONE place —
    ``distkeras_tpu.analysis.unused_imports`` (real ruff when the
    container has it, else an AST F401 sweep + compile check) — and
    these named cells delegate, so there is one F401 implementation
    instead of N copies while a scoping change can never silently drop
    a package (the cell names are the coverage contract)."""
    import os

    from distkeras_tpu.analysis import unused_imports as ui

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert package in ui.PACKAGES, \
        f"cell {package!r} dropped from analysis/unused_imports.PACKAGES"
    assert ui.package_files(root, package), \
        f"package {package!r} resolves to no files — coverage went hollow"
    findings = ui.check_package(root, package)
    assert not findings, "\n".join(str(f) for f in findings)


@pytest.mark.parametrize("module", ["streaming.py", "job_deployment.py"])
def test_runtime_stragglers_lint_clean_named(module):
    """Satellite (PR 11, delegated to the one F401 implementation by
    PR 12): the runtime modules named by ISSUE 11 — streaming.py and
    job_deployment.py — keep their own NAMED lint cells so a future
    scoping change to the package-level sweep can never silently drop
    them (the package cell scans by listdir; this one pins the two
    files by name)."""
    import os

    from distkeras_tpu.analysis import unused_imports as ui

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "distkeras_tpu", "runtime", module)
    assert os.path.exists(path), f"{module} moved without updating the guard"
    findings = ui.check_files([path], root)
    assert not findings, "\n".join(str(f) for f in findings)


def test_telemetry_disabled_leaves_async_run_unrecorded(toy_dataset):
    """Disabled-by-default contract: the instrumented async path records
    nothing unless enabled (and still trains correctly)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    obs.reset()
    assert not obs.enabled()
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    trainer = dk.AsyncADAG(Model.init(spec, seed=0),
                           loss="categorical_crossentropy", batch_size=16,
                           num_epoch=1, num_workers=2, communication_window=4,
                           learning_rate=0.05, seed=0)
    trainer.train(toy_dataset)
    assert len(trainer.history) > 0
    snap = obs.snapshot()
    assert snap["counters"].get("ps_commits_total", 0.0) == 0.0
    assert len(obs.TRACER.events()) == 0
