"""Unified telemetry subsystem (ISSUE #1): registry semantics, span
tracing, exporters, and the end-to-end async-trainer acceptance path.

The end-to-end test is the ISSUE's acceptance criterion verbatim: a
CPU-slice ``AsyncADAG`` run (2 workers, >=3 windows) must export a valid
Chrome trace (``json.loads``-able, ``ph``/``ts``/``dur`` events for window
and pull/commit spans) and a metrics snapshot with nonzero
``ps_commits_total``, ``ps_pull_bytes_total``, the per-window wall-vs-
device histograms, and the prefetch queue-depth gauge.
"""

import json
import threading
import time

import numpy as np
import pytest

from distkeras_tpu import observability as obs
from distkeras_tpu.observability import (
    DEFAULT_BUCKETS,
    JsonlFlusher,
    MetricsRegistry,
    SpanTracer,
)


@pytest.fixture
def telemetry():
    """Enable the process-global registry/tracer for one test, leaving a
    clean disabled slate afterwards (other tests must keep paying only the
    disabled-mode branch)."""
    obs.reset()
    obs.enable()
    yield obs
    obs.disable()
    obs.reset()


# -- registry semantics -------------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("commits_total")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)

    g = reg.gauge("depth")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3.0

    h = reg.histogram("lat_seconds")
    for v in (0.001, 0.01, 0.01, 5.0):
        h.observe(v)
    s = h.summary()
    assert s["count"] == 4 and s["min"] == 0.001 and s["max"] == 5.0
    assert s["sum"] == pytest.approx(5.021)
    # cumulative bucket counts are monotone and end at count
    cums = [c for _, c in s["buckets"]]
    assert cums == sorted(cums) and cums[-1] == 4


def test_histogram_boundary_value_lands_in_its_le_bucket():
    reg = MetricsRegistry(enabled=True)
    h = reg.histogram("h")
    h.observe(DEFAULT_BUCKETS[10])  # exactly a bound: le is inclusive
    assert [DEFAULT_BUCKETS[10], 1] in h.summary()["buckets"]


def test_labels_create_distinct_instruments():
    reg = MetricsRegistry(enabled=True)
    reg.gauge("stale", worker="0").set(1)
    reg.gauge("stale", worker="1").set(7)
    assert reg.value("stale", worker="0") == 1.0
    assert reg.value("stale", worker="1") == 7.0
    assert reg.value("stale", worker="2") is None  # value() never creates
    snap = reg.snapshot()
    assert snap["gauges"]['stale{worker="0"}'] == 1.0
    assert snap["gauges"]['stale{worker="1"}'] == 7.0


def test_kind_conflict_raises():
    reg = MetricsRegistry(enabled=True)
    reg.counter("x_total")
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("x_total")


def test_disabled_registry_is_noop():
    reg = MetricsRegistry(enabled=False)
    c, g, h = reg.counter("c"), reg.gauge("g"), reg.histogram("h")
    c.inc(5)
    g.set(9)
    h.observe(1.0)
    assert c.value == 0.0 and g.value == 0.0 and h.count == 0
    # flipping the switch makes the SAME cached instruments live
    reg.enabled = True
    c.inc(5)
    assert c.value == 5.0


def test_thread_safety_under_concurrent_writers():
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("n_total")
    h = reg.histogram("v")

    def writer(i):
        for k in range(1000):
            c.inc()
            h.observe(0.001 * (i + 1))

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000


def test_prometheus_rendering():
    reg = MetricsRegistry(enabled=True)
    reg.counter("pulls_total").inc(3)
    reg.gauge("stale", worker="0").set(2)
    reg.histogram("lat_seconds").observe(0.01)
    text = reg.render_prometheus()
    assert "# TYPE pulls_total counter" in text
    assert "pulls_total 3.0" in text
    assert '# TYPE stale gauge' in text and 'stale{worker="0"} 2.0' in text
    assert "# TYPE lat_seconds histogram" in text
    assert 'lat_seconds_bucket{le="+Inf"} 1' in text
    assert "lat_seconds_count 1" in text


# -- span tracer --------------------------------------------------------------

def test_span_nesting_records_depth_and_containment():
    tr = SpanTracer(capacity=64, enabled=True)
    with tr.span("outer", kind="epoch"):
        with tr.span("inner"):
            time.sleep(0.001)
    inner, outer = tr.events()  # inner exits (and records) first
    assert inner["name"] == "inner" and outer["name"] == "outer"
    assert inner["depth"] == 1 and outer["depth"] == 0
    assert inner["ts_us"] >= outer["ts_us"]
    assert inner["ts_us"] + inner["dur_us"] <= outer["ts_us"] + outer["dur_us"] + 1
    assert outer["attrs"] == {"kind": "epoch"}


def test_ring_buffer_eviction_keeps_newest_and_counts_drops():
    tr = SpanTracer(capacity=4, enabled=True)
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    assert len(tr) == 4
    assert [e["name"] for e in tr.events()] == ["s6", "s7", "s8", "s9"]
    assert tr.dropped == 6


def test_disabled_tracer_records_nothing():
    tr = SpanTracer(capacity=4, enabled=False)
    with tr.span("x"):
        pass
    assert len(tr) == 0


def test_chrome_trace_export_is_valid_trace_event_json(tmp_path):
    tr = SpanTracer(capacity=16, enabled=True)
    with tr.span("a", worker=0):
        pass
    path = tr.export_chrome(str(tmp_path / "trace.json"))
    with open(path) as f:
        parsed = json.loads(f.read())
    assert isinstance(parsed["traceEvents"], list) and parsed["traceEvents"]
    for ev in parsed["traceEvents"]:
        assert ev["ph"] == "X"
        assert isinstance(ev["ts"], int) and isinstance(ev["dur"], int)
        assert "pid" in ev and "tid" in ev and "name" in ev


def test_jsonl_export_and_drain(tmp_path):
    tr = SpanTracer(capacity=16, enabled=True)
    for name in ("a", "b"):
        with tr.span(name):
            pass
    lines = list(tr.jsonl())
    assert [json.loads(l)["name"] for l in lines] == ["a", "b"]
    drained = tr.drain()
    assert len(drained) == 2 and len(tr) == 0


def test_span_error_annotated():
    tr = SpanTracer(enabled=True)
    with pytest.raises(RuntimeError):
        with tr.span("boom"):
            raise RuntimeError("x")
    (ev,) = tr.events()
    assert ev["attrs"]["error"] == "RuntimeError"


def test_jsonl_flusher_writes_selfcontained_lines(tmp_path):
    reg = MetricsRegistry(enabled=True)
    tr = SpanTracer(enabled=True)
    reg.counter("c_total").inc(2)
    with tr.span("s"):
        pass
    path = str(tmp_path / "telemetry.jsonl")
    flusher = JsonlFlusher(path, reg, tracer=tr, interval=60.0)
    flusher.start()
    flusher.stop()  # final flush
    with open(path) as f:
        lines = [json.loads(l) for l in f.read().splitlines()]
    assert lines, "stop() must land at least one flush"
    assert lines[0]["metrics"]["counters"]["c_total"] == 2.0
    assert [s["name"] for s in lines[0]["spans"]] == ["s"]
    # spans are drained: a second flush does not repeat them
    flusher.flush()
    with open(path) as f:
        lines = [json.loads(l) for l in f.read().splitlines()]
    assert "spans" not in lines[-1]


# -- instrumented layers ------------------------------------------------------

def test_prefetch_feed_gauges_and_chunk_latency(telemetry, toy_dataset):
    from distkeras_tpu.data.dataset import prefetch_to_device

    chunks = toy_dataset.chunked_epoch(16, ["features", "label"],
                                      window=1, chunk_windows=8)
    seen = 0
    for _ in prefetch_to_device(chunks, lambda ch: ch["features"].shape):
        seen += 1
    assert seen == 8
    snap = obs.snapshot()
    assert snap["counters"]["feed_chunks_total"] == 8.0
    assert "feed_queue_depth" in snap["gauges"]
    assert snap["histograms"]["feed_chunk_load_seconds"]["count"] == 8


def test_prefetch_raises_when_producer_dies_without_sentinel(monkeypatch):
    """ADVICE round 5: a producer killed without its 'done'/'error'
    sentinel must surface as an error, not a silent q.get() hang."""
    from distkeras_tpu.data.dataset import prefetch_to_device

    class DeadThread:
        def __init__(self, *a, **kw):
            pass

        def start(self):
            pass  # never runs: simulates death-before-first-put

        def is_alive(self):
            return False

    monkeypatch.setattr(threading, "Thread", DeadThread)
    it = prefetch_to_device(iter([{"x": 1}]), lambda ch: ch)
    with pytest.raises(RuntimeError, match="producer thread died"):
        next(it)


def test_head_recompute_factor_formula():
    from distkeras_tpu.parallel.pipeline import head_recompute_factor

    # round 6: the 1F1B head + CE runs in a lax.cond taken only on the
    # last rank's valid backward units — exactly M evaluations per step,
    # same as GPipe, so the factor is 1.0 at EVERY (pp, M).  The round-5
    # where-masked schedule measured pp * (1 + 2(pp-1)/M); if this
    # assertion ever needs a formula again, head recompute came back
    assert head_recompute_factor(1, 8) == 1.0
    assert head_recompute_factor(2, 8) == 1.0
    assert head_recompute_factor(4, 8) == 1.0
    with pytest.raises(ValueError):
        head_recompute_factor(0, 8)


def test_punchcard_telemetry_action(telemetry, tmp_path):
    from distkeras_tpu.runtime.job_deployment import Punchcard, fetch_telemetry

    obs.counter("ps_commits_total").inc(3)
    with obs.span("async.window", worker=0):
        pass
    pc = Punchcard(secret="s3cret").start()
    try:
        resp = fetch_telemetry("127.0.0.1", pc.port, "s3cret",
                               trace=True, prometheus=True)
    finally:
        pc.stop()
    assert resp["enabled"] is True
    assert resp["metrics"]["counters"]["ps_commits_total"] == 3.0
    assert any(e["name"] == "async.window"
               for e in resp["trace"]["traceEvents"])
    assert "ps_commits_total 3.0" in resp["prometheus"]


# -- end-to-end acceptance: AsyncADAG smoke run -------------------------------

def test_async_adag_smoke_exports_metrics_and_chrome_trace(telemetry, toy_dataset,
                                                           tmp_path):
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    trainer = dk.AsyncADAG(Model.init(spec, seed=0),
                           loss="categorical_crossentropy", batch_size=16,
                           num_epoch=1, num_workers=2, communication_window=4,
                           learning_rate=0.05, seed=0)
    trainer.train(toy_dataset)
    # 1024 rows / 2 workers / (16 batch * 4 window) = 8 windows per worker
    assert len(trainer.history) >= 3 * 2

    snap = obs.snapshot()
    assert snap["counters"]["ps_commits_total"] > 0
    assert snap["counters"]["ps_pull_bytes_total"] > 0
    assert snap["counters"]["ps_commit_bytes_total"] > 0
    # issue-3 client-side hot-path instruments (exported through the same
    # registry the telemetry punchcard action snapshots)
    assert snap["counters"]["ps.commit_bytes"] > 0
    assert snap["histograms"]["ps.pull_latency_ms"]["count"] > 0
    assert snap["histograms"]["ps.commit_latency_ms"]["count"] > 0
    assert snap["histograms"]["ps.serialize_ms"]["count"] > 0
    assert "ps.inflight_depth" in snap["gauges"]
    # hub-side staleness distribution: one observation per applied commit
    assert snap["histograms"]["ps_commit_staleness"]["count"] \
        == snap["counters"]["ps_commits_total"]
    wall = snap["histograms"]["async_window_wall_seconds"]
    dev = snap["histograms"]["async_window_device_seconds"]
    assert wall["count"] >= 3 and dev["count"] >= 3
    assert wall["sum"] >= dev["sum"]  # the wall leg contains the device leg
    assert any(k.startswith("ps_staleness{") for k in snap["gauges"])
    # the async worker feed rides the shared prefetch machinery under its
    # own metric prefix (so window staging cannot pollute the disk feed's
    # instruments), and the prefetch queue-depth gauge populates in an
    # async-only run too
    assert "async_feed_queue_depth" in snap["gauges"]
    assert snap["counters"]["async_feed_chunks_total"] > 0
    assert snap["counters"]['trainer_epochs_total{trainer="AsyncADAG"}'] == 1.0
    assert snap["histograms"]['trainer_window_loss{trainer="AsyncADAG"}']["count"] \
        == len(trainer.history)

    # the exported Chrome trace parses and carries complete (ph/ts/dur)
    # events for the window and pull/commit spans
    path = obs.TRACER.export_chrome(str(tmp_path / "smoke_trace.json"))
    with open(path) as f:
        parsed = json.loads(f.read())
    names = {e["name"] for e in parsed["traceEvents"]}
    assert {"async.window", "ps.pull", "ps.commit"} <= names
    for ev in parsed["traceEvents"]:
        assert ev["ph"] == "X" and "ts" in ev and "dur" in ev

    # the wall/device decomposition is coherent per window: device time
    # never exceeds wall time
    assert dev["max"] <= wall["max"] * 1.001


def test_telemetry_disabled_leaves_async_run_unrecorded(toy_dataset):
    """Disabled-by-default contract: the instrumented async path records
    nothing unless enabled (and still trains correctly)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    obs.reset()
    assert not obs.enabled()
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    trainer = dk.AsyncADAG(Model.init(spec, seed=0),
                           loss="categorical_crossentropy", batch_size=16,
                           num_epoch=1, num_workers=2, communication_window=4,
                           learning_rate=0.05, seed=0)
    trainer.train(toy_dataset)
    assert len(trainer.history) > 0
    snap = obs.snapshot()
    assert snap["counters"].get("ps_commits_total", 0.0) == 0.0
    assert len(obs.TRACER.events()) == 0
