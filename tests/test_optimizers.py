"""Optimizer registry and learning-rate schedules."""

import numpy as np
import optax
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.base import ModelSpec
from distkeras_tpu.ops.optimizers import get_optimizer, get_schedule
from distkeras_tpu.trainers import AEASGD, SingleTrainer


def test_all_registry_names_build_and_step():
    import jax.numpy as jnp

    names = ["sgd", "momentum", "nesterov", "adam", "adamw", "adamax",
             "nadam", "adagrad", "rmsprop", "adadelta", "lamb", "lars", "lion"]
    params = {"w": jnp.ones((4,))}
    grads = {"w": jnp.full((4,), 0.5)}
    for name in names:
        opt = get_optimizer(name, learning_rate=0.1)
        state = opt.init(params)
        updates, _ = opt.update(grads, state, params)
        assert np.isfinite(np.asarray(updates["w"])).all(), name


def test_unknown_name_and_passthrough():
    with pytest.raises(ValueError, match="unknown optimizer"):
        get_optimizer("sgdd")
    obj = optax.sgd(0.1)
    assert get_optimizer(obj) is obj


def test_schedules_shapes():
    s = get_schedule("cosine", 0.1, decay_steps=100, warmup_steps=10)
    assert float(s(0)) == 0.0                      # warmup starts at 0
    assert abs(float(s(10)) - 0.1) < 1e-6          # peak after warmup
    assert float(s(110)) < 0.01                    # decayed
    lin = get_schedule("linear", 0.2, decay_steps=10, end_value=0.02)
    assert abs(float(lin(10)) - 0.02) < 1e-6
    exp = get_schedule("exponential", 0.1, decay_steps=10, decay_rate=0.5)
    assert abs(float(exp(10)) - 0.05) < 1e-6
    floored = get_schedule("exponential", 0.1, decay_steps=10, decay_rate=0.5,
                           end_value=0.05)
    assert float(floored(100)) == pytest.approx(0.05)
    const = get_schedule("constant", 0.3, decay_steps=1)
    assert float(const(999)) == pytest.approx(0.3)
    with pytest.raises(ValueError, match="unknown schedule"):
        get_schedule("staircase", 0.1, 10)


def test_trainer_accepts_schedule_as_learning_rate():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 64)]
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 2},
                     input_shape=(8,))
    sched = get_schedule("cosine", 0.05, decay_steps=20, warmup_steps=2)
    tr = SingleTrainer(spec, learning_rate=sched, batch_size=16, num_epoch=3)
    model = tr.train(Dataset({"features": x, "label": y}))
    assert np.isfinite(tr.history).all()
    assert model.apply(x[:2]).shape == (2, 2)


def test_elastic_trainers_reject_schedule_learning_rate():
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 2},
                     input_shape=(8,))
    sched = get_schedule("cosine", 0.05, decay_steps=20)
    with pytest.raises(ValueError, match="scalar learning_rate"):
        AEASGD(spec, learning_rate=sched, num_workers=2)
