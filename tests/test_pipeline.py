"""GPipe pipeline-parallel LM step: must match the single-device step."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distkeras_tpu.models.base import Model
from distkeras_tpu.models.transformer import small_lm_spec
from distkeras_tpu.parallel.mesh import create_nd_mesh
from distkeras_tpu.parallel.pipeline import (
    make_pp_train_step, merge_block_params, pp_state_shardings, split_block_params)
from distkeras_tpu.parallel.lm import shift_targets


def _spec(num_layers=4):
    return small_lm_spec(vocab_size=64, model_dim=32, num_heads=2,
                         num_layers=num_layers, max_seq_len=16)


def test_split_merge_roundtrip():
    spec = _spec()
    params = Model.init(spec, seed=0).params
    outer, blocks = split_block_params(params)
    assert jax.tree.leaves(blocks)[0].shape[0] == 4
    merged = merge_block_params(outer, blocks)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(merged)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pp_step_matches_single_device():
    mesh = create_nd_mesh((2, 4), ("dp", "pp"))
    spec = _spec(num_layers=4)
    model = Model.init(spec, seed=0)
    opt = optax.sgd(0.1)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, size=(8, 16)).astype(np.int32)
    targets = shift_targets(tokens)

    # single-device reference
    module = spec.build()

    def loss_fn(params, tok, tgt):
        logits = module.apply({"params": params}, tok)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), tgt)
        return ce[:, :-1].mean()

    loss_ref, grads = jax.value_and_grad(loss_fn)(model.params, tokens, targets)
    updates, _ = opt.update(grads, opt.init(model.params), model.params)
    params_ref = optax.apply_updates(model.params, updates)

    # pipeline step: 4 stages x 1 layer, 2 microbatches per dp shard
    outer, blocks = split_block_params(model.params)
    step = make_pp_train_step(spec, opt, mesh, num_microbatches=2)
    psh, osh = pp_state_shardings(mesh, opt, outer, blocks)
    params = jax.device_put((outer, blocks), psh)
    opt_state = jax.device_put(opt.init((outer, blocks)), osh)
    from jax.sharding import NamedSharding, PartitionSpec as P

    dsh = NamedSharding(mesh, P("dp"))
    (outer2, blocks2), _, loss = step(params, opt_state,
                                      jax.device_put(tokens, dsh),
                                      jax.device_put(targets, dsh))

    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-3)
    merged = merge_block_params(jax.tree.map(np.asarray, outer2),
                                jax.tree.map(np.asarray, blocks2))
    for (ka, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(merged),
                               jax.tree_util.tree_leaves_with_path(params_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3,
                                   err_msg=f"param mismatch at {jax.tree_util.keystr(ka)}")


def test_1f1b_matches_gpipe_and_single_device():
    """The hand-scheduled 1F1B backward must produce the same loss and
    updated params as GPipe's autodiff backward AND the single-device
    reference — the schedules differ only in memory shape."""
    mesh = create_nd_mesh((2, 4), ("dp", "pp"))
    spec = _spec(num_layers=4)
    model = Model.init(spec, seed=0)
    opt = optax.sgd(0.1)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, size=(8, 16)).astype(np.int32)
    targets = shift_targets(tokens)

    module = spec.build()

    def loss_fn(params, tok, tgt):
        logits = module.apply({"params": params}, tok)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), tgt)
        return ce[:, :-1].mean()

    loss_ref, grads = jax.value_and_grad(loss_fn)(model.params, tokens, targets)
    updates, _ = opt.update(grads, opt.init(model.params), model.params)
    params_ref = optax.apply_updates(model.params, updates)

    from jax.sharding import NamedSharding, PartitionSpec as P
    dsh = NamedSharding(mesh, P("dp"))
    results = {}
    for schedule in ("gpipe", "1f1b"):
        # fresh buffers each schedule: the donated step may alias (and
        # delete) the arrays device_put was handed
        outer, blocks = split_block_params(
            jax.tree.map(jnp.array, model.params))
        step = make_pp_train_step(spec, opt, mesh, num_microbatches=4,
                                  schedule=schedule)
        psh, osh = pp_state_shardings(mesh, opt, outer, blocks)
        params = jax.device_put((outer, blocks), psh)
        opt_state = jax.device_put(opt.init((outer, blocks)), osh)
        (outer2, blocks2), _, loss = step(params, opt_state,
                                          jax.device_put(tokens, dsh),
                                          jax.device_put(targets, dsh))
        results[schedule] = (float(loss), merge_block_params(
            jax.tree.map(np.asarray, outer2), jax.tree.map(np.asarray, blocks2)))

    for schedule, (loss, merged) in results.items():
        np.testing.assert_allclose(loss, float(loss_ref), rtol=1e-3,
                                   err_msg=f"{schedule} loss vs single-device")
        for (ka, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(merged),
                                   jax.tree_util.tree_leaves_with_path(params_ref)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-3,
                err_msg=f"{schedule} param mismatch at {jax.tree_util.keystr(ka)}")
    # and against each other (same math, different bf16 accumulation
    # order — the schedules chain cotangents through different sequences,
    # so they are no closer to each other than to the f32 reference)
    for (ka, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(results["gpipe"][1]),
            jax.tree_util.tree_leaves_with_path(results["1f1b"][1])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-3,
            err_msg=f"gpipe vs 1f1b mismatch at {jax.tree_util.keystr(ka)}")


def test_1f1b_learns_and_rejects_unknown_schedule():
    import pytest

    mesh = create_nd_mesh((2, 2), ("dp", "pp"))
    spec = _spec(num_layers=2)
    with pytest.raises(ValueError, match="schedule"):
        make_pp_train_step(spec, optax.sgd(0.1), mesh, num_microbatches=2,
                           schedule="zigzag")
    model = Model.init(spec, seed=1)
    opt = optax.adam(1e-2)
    outer, blocks = split_block_params(model.params)
    step = make_pp_train_step(spec, opt, mesh, num_microbatches=2,
                              schedule="1f1b")
    psh, osh = pp_state_shardings(mesh, opt, outer, blocks)
    params = jax.device_put((outer, blocks), psh)
    opt_state = jax.device_put(opt.init((outer, blocks)), osh)
    from jax.sharding import NamedSharding, PartitionSpec as P
    dsh = NamedSharding(mesh, P("dp"))
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 8, size=(8, 16)).astype(np.int32)
    targets = shift_targets(tokens)
    tok_d, tgt_d = jax.device_put(tokens, dsh), jax.device_put(targets, dsh)
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, tok_d, tgt_d)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_pp_step_learns():
    mesh = create_nd_mesh((2, 2), ("dp", "pp"))
    spec = _spec(num_layers=2)
    model = Model.init(spec, seed=1)
    opt = optax.adam(1e-2)
    outer, blocks = split_block_params(model.params)
    step = make_pp_train_step(spec, opt, mesh, num_microbatches=2)
    psh, osh = pp_state_shardings(mesh, opt, outer, blocks)
    params = jax.device_put((outer, blocks), psh)
    opt_state = jax.device_put(opt.init((outer, blocks)), osh)
    from jax.sharding import NamedSharding, PartitionSpec as P

    dsh = NamedSharding(mesh, P("dp"))
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 8, size=(8, 16)).astype(np.int32)
    targets = shift_targets(tokens)
    tok_d, tgt_d = jax.device_put(tokens, dsh), jax.device_put(targets, dsh)

    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, tok_d, tgt_d)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
