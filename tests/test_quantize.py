"""Weight-only int8 quantization: error bounds, size, serving parity."""

import jax.numpy as jnp
import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.base import Model, ModelSpec
from distkeras_tpu.models.cnn import mnist_cnn_spec
from distkeras_tpu.ops.quantize import (QTensor, dequantize_params,
                                        param_nbytes, quantization_error,
                                        quantize_leaf, quantize_params)
from distkeras_tpu.predictors import ModelPredictor


def test_quantize_leaf_roundtrip_error_small():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(64, 128)) * 0.2, jnp.float32)
    qt = quantize_leaf(w)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (1, 128)
    err = np.abs(np.asarray(qt.dequantize()) - np.asarray(w))
    # per-channel symmetric int8: error bounded by scale/2 per element
    assert np.all(err <= np.asarray(qt.scale)[0] * 0.5 + 1e-7)


def test_per_channel_beats_per_tensor_on_skewed_channels():
    """A channel 100x smaller than its neighbors keeps ~8 bits of its own
    range — the point of per-channel scales."""
    rng = np.random.default_rng(1)
    w = rng.normal(size=(32, 4)).astype(np.float32)
    w[:, 0] *= 0.01
    qt = quantize_leaf(jnp.asarray(w))
    deq = np.asarray(qt.dequantize())
    rel = np.linalg.norm(deq[:, 0] - w[:, 0]) / np.linalg.norm(w[:, 0])
    assert rel < 0.01


def test_quantize_params_selects_weights_only():
    model = Model.init(mnist_cnn_spec(), seed=0)
    qp = quantize_params(model.params, min_size=1024)
    # dense kernels quantized; biases and small conv kernels untouched
    assert isinstance(qp["Dense_0"]["kernel"], QTensor)
    assert not isinstance(qp["Dense_0"]["bias"], QTensor)
    assert quantization_error(model.params, qp) < 0.01
    assert param_nbytes(qp) < 0.3 * param_nbytes(model.params)
    deq = dequantize_params(qp)
    assert deq["Dense_0"]["kernel"].shape == model.params["Dense_0"]["kernel"].shape


def test_quantized_predictor_matches_full_precision():
    from distkeras_tpu.ops.quantize import QTensor as QT

    rng = np.random.default_rng(2)
    x = rng.normal(size=(256, 128)).astype(np.float32)
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (512, 256), "num_outputs": 4},
                     input_shape=(128,))
    model = Model.init(spec, seed=3)
    ds = Dataset({"features": x})
    full = ModelPredictor(model).predict(ds)["prediction"]
    pq = ModelPredictor(model, quantize=True)
    # the serving path must actually be quantized, or this test is vacuous
    import jax
    n_q = sum(isinstance(l, QT)
              for l in jax.tree.leaves(pq._params, is_leaf=lambda l: isinstance(l, QT)))
    assert n_q >= 2, f"expected quantized kernels in the serving tree, got {n_q}"
    quant = pq.predict(ds)["prediction"]
    # logits drift a little; the served class must not (on a margin-y task)
    denom = np.maximum(np.abs(full).max(), 1e-6)
    assert np.abs(full - quant).max() / denom < 0.05
    assert 0 < np.abs(full - quant).max(), "outputs identical — nothing was quantized"
    assert (np.argmax(full, axis=1) == np.argmax(quant, axis=1)).mean() > 0.97


def test_quantize_min_size_plumbs_through_predictor():
    from distkeras_tpu.ops.quantize import QTensor as QT
    import jax

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (64, 32), "num_outputs": 4},
                     input_shape=(16,))
    model = Model.init(spec, seed=0)
    # default threshold: these tiny kernels stay dense
    assert not any(isinstance(l, QT) for l in jax.tree.leaves(
        ModelPredictor(model, quantize=True)._params,
        is_leaf=lambda l: isinstance(l, QT)))
    # lowered threshold: they quantize
    assert any(isinstance(l, QT) for l in jax.tree.leaves(
        ModelPredictor(model, quantize=True, quantize_min_size=128)._params,
        is_leaf=lambda l: isinstance(l, QT)))


def test_unquantized_predictor_reads_params_live():
    """A predictor built before (re)training serves the model's CURRENT
    weights — the pre-quantization behavior, preserved."""
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 2},
                     input_shape=(4,))
    model = Model.init(spec, seed=0)
    pred = ModelPredictor(model)
    x = np.ones((4, 4), np.float32)
    before = pred.predict(Dataset({"features": x}))["prediction"]
    model.params = Model.init(spec, seed=9).params
    after = pred.predict(Dataset({"features": x}))["prediction"]
    assert np.abs(before - after).max() > 0
