"""Recurrent family: shapes, learning, serialization, trainer integration."""

import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.base import Model
from distkeras_tpu.models.rnn import feature_rnn_spec, lstm_classifier_spec
from distkeras_tpu.trainers import ADAG, SingleTrainer


def test_lstm_classifier_shapes_and_roundtrip():
    spec = lstm_classifier_spec(vocab_size=50, seq_len=12, embed_dim=16,
                                hidden_sizes=(24, 16), num_outputs=3)
    m = Model.init(spec, seed=0)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 50, (4, 12)))
    logits = m.apply(toks)
    assert logits.shape == (4, 3)
    m2 = Model.deserialize(m.serialize())
    np.testing.assert_array_equal(np.asarray(m2.apply(toks)), np.asarray(logits))


def test_gru_feature_model_shapes():
    spec = feature_rnn_spec(seq_len=10, feature_dim=5, hidden_sizes=(8,),
                            num_outputs=2, cell_type="gru")
    m = Model.init(spec, seed=0)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(3, 10, 5)), jnp.float32)
    assert m.apply(x).shape == (3, 2)


def test_bad_cell_type_rejected():
    spec = lstm_classifier_spec(cell_type="elman")
    with pytest.raises(ValueError, match="cell_type"):
        Model.init(spec, seed=0)


def _token_parity_data(n, seq_len, vocab, seed):
    """Label = whether token 0 appears an even number of times — genuinely
    sequential (a bag-of-words linear head can't do it; an LSTM can)."""
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, vocab, (n, seq_len)).astype(np.int32)
    labels = ((toks == 0).sum(axis=1) % 2 == 0).astype(np.int64)
    onehot = np.eye(2, dtype=np.float32)[labels]
    return toks, onehot, labels


def test_lstm_learns_sequential_task_with_single_trainer():
    toks, onehot, labels = _token_parity_data(512, 8, 4, seed=0)
    spec = lstm_classifier_spec(vocab_size=4, seq_len=8, embed_dim=16,
                                hidden_sizes=(32,), num_outputs=2)
    tr = SingleTrainer(spec, loss="categorical_crossentropy",
                       worker_optimizer="adam", learning_rate=3e-3,
                       batch_size=64, num_epoch=30, seed=1)
    model = tr.train(Dataset({"features": toks, "label": onehot}))
    pred = np.argmax(np.asarray(model.apply(jnp.asarray(toks))), axis=1)
    acc = (pred == labels).mean()
    assert acc > 0.9, f"LSTM failed to learn parity task: acc {acc}"


def test_gru_trains_under_distributed_trainer():
    toks, onehot, _ = _token_parity_data(256, 8, 4, seed=2)
    spec = lstm_classifier_spec(vocab_size=4, seq_len=8, embed_dim=8,
                                hidden_sizes=(16,), num_outputs=2,
                                cell_type="gru")
    tr = ADAG(spec, num_workers=8, batch_size=16, num_epoch=2,
              communication_window=2, learning_rate=0.01)
    model = tr.train(Dataset({"features": toks, "label": onehot}))
    assert np.isfinite(tr.history).all()
    assert model.apply(jnp.asarray(toks[:4])).shape == (4, 2)
