"""Rotary position embeddings (ops/rotary.py + positional="rope").

Pins: the rotation's defining algebraic properties, the no-table param
tree, cached decode == the training forward's argmax (the decode-path
identity), sequence-parallel global positions, and composition with GQA
and the pipeline schedules.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models.base import Model
from distkeras_tpu.models.decode import generate, make_generate_fn
from distkeras_tpu.models.transformer import small_lm_spec
from distkeras_tpu.ops.rotary import rope_rotate

VOCAB, D, H, LAYERS = 61, 32, 2, 2


def _rope_spec(**kw):
    cfg = dict(vocab_size=VOCAB, model_dim=D, num_heads=H, num_layers=LAYERS,
               max_seq_len=48, positional="rope")
    cfg.update(kw)
    spec = small_lm_spec(**cfg)
    spec.config["compute_dtype"] = "float32"
    return spec


def test_rotation_properties():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 8, 3, 16)), jnp.float32)
    # position 0 is the identity
    np.testing.assert_allclose(np.asarray(rope_rotate(x, jnp.zeros(8, jnp.int32))),
                               np.asarray(x), rtol=1e-6)
    # rotations preserve vector norms
    pos = jnp.asarray([0, 3, 7, 11, 100, 1000, 5000, 9999], jnp.int32)
    r = rope_rotate(x, pos)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(r), axis=-1),
                               np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)
    # the score depends only on the RELATIVE offset: <R(p)q, R(p+d)k> is
    # invariant to shifting both positions
    q = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)

    def score(pq, pk):
        rq = rope_rotate(q, jnp.asarray([pq], jnp.int32))
        rk = rope_rotate(k, jnp.asarray([pk], jnp.int32))
        return float(jnp.sum(rq * rk))

    assert score(3, 10) == pytest.approx(score(20, 27), rel=1e-4)
    assert score(0, 5) == pytest.approx(score(95, 100), rel=1e-4)
    # and genuinely DEPENDS on the offset
    assert abs(score(3, 10) - score(3, 4)) > 1e-4
    with pytest.raises(ValueError, match="even"):
        rope_rotate(x[..., :15], pos)


@pytest.mark.slow  # tier-1 budget fix (PR 11): heaviest cells ride the full suite
def test_rope_tree_has_no_table_and_model_learns():
    model = Model.init(_rope_spec(), seed=0)
    assert "pos_embed" not in model.params
    import optax
    from distkeras_tpu.ops.losses import lm_token_cross_entropy
    from distkeras_tpu.parallel.lm import shift_targets

    module = model.spec.build()
    toks = np.random.default_rng(1).integers(0, VOCAB, (4, 16)).astype(np.int32)
    tgts = jnp.asarray(shift_targets(toks))
    toks = jnp.asarray(toks)
    opt = optax.adam(1e-2)

    def loss_fn(p):
        return lm_token_cross_entropy(module, p, toks, tgts)[:, :-1].mean()

    params = jax.tree.map(jnp.asarray, model.params)
    state = opt.init(params)
    losses = []
    for _ in range(30):
        l, g = jax.value_and_grad(loss_fn)(params)
        up, state = opt.update(g, state, params)
        params = jax.tree.map(lambda a, b: a + b, params, up)
        losses.append(float(l))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7


def test_cached_decode_matches_training_forward():
    """The decode-path identity: greedy generation through the KV cache
    (rotated-K rows) equals stepwise argmax of the TRAINING forward over
    the growing sequence — position math must agree exactly."""
    model = Model.init(_rope_spec(), seed=3)
    prompt = np.asarray([[5, 17, 3], [40, 2, 21]], np.int32)
    got = np.asarray(generate(model, jnp.asarray(prompt), max_new_tokens=8))
    seq = prompt.copy()
    for _ in range(8):
        logits = np.asarray(model.apply(jnp.asarray(seq)))
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, seq[:, prompt.shape[1]:])


def test_rope_quantized_cache_and_gqa_decode():
    """RoPE composes with the int8 cache (rows quantized AFTER rotation)
    and with GQA (rotation is head-count agnostic)."""
    spec = _rope_spec(num_kv_heads=1, num_heads=2)
    model = Model.init(spec, seed=4)
    prompt = jnp.asarray([[9, 9, 10]], jnp.int32)
    plain = np.asarray(make_generate_fn(spec, 8)(model.params, prompt))
    quant = np.asarray(make_generate_fn(spec, 8, quantize_cache=True)(
        model.params, prompt))
    # int8 KV is an approximation; on this tiny model greedy argmaxes agree
    np.testing.assert_array_equal(plain, quant)
    # and the cache really is Hkv-headed
    from distkeras_tpu.models.decode import init_cache
    assert init_cache(dict(spec.config), 1, 16).k.shape[3] == 1


def test_rope_under_sequence_parallelism_matches_single_device():
    """Global positions under sp: the sharded loss equals the unsharded
    loss — each shard rotates by rank * L_local + local index."""
    import optax
    from distkeras_tpu.ops.losses import lm_token_cross_entropy
    from distkeras_tpu.parallel.lm import (lm_data_shardings, lm_state_shardings,
                                           make_lm_train_step, shift_targets)
    from distkeras_tpu.parallel.mesh import create_nd_mesh

    mesh = create_nd_mesh((2, 2), ("dp", "sp"))
    spec = small_lm_spec(vocab_size=VOCAB, model_dim=D, num_heads=H,
                         num_layers=2, max_seq_len=16, positional="rope",
                         seq_axis="sp")
    spec.config["compute_dtype"] = "float32"
    model = Model.init(spec, seed=1)
    toks = np.random.default_rng(2).integers(0, VOCAB, (4, 16)).astype(np.int32)
    tgts = shift_targets(toks)

    # unsharded reference loss over the SAME batch
    ref_spec = small_lm_spec(vocab_size=VOCAB, model_dim=D, num_heads=H,
                             num_layers=2, max_seq_len=16, positional="rope")
    ref_spec.config["compute_dtype"] = "float32"
    module = ref_spec.build()
    ref = float(lm_token_cross_entropy(module, model.params, jnp.asarray(toks),
                                       jnp.asarray(tgts))[:, :-1].mean())

    opt = optax.sgd(0.0)  # lr 0: read the loss without moving params
    step = make_lm_train_step(spec, opt, mesh, sp_axis="sp")
    psh, osh = lm_state_shardings(mesh, opt, model.params)
    params = jax.device_put(jax.tree.map(jnp.asarray, model.params), psh)
    opt_state = jax.device_put(opt.init(params), osh)
    dsh = lm_data_shardings(mesh, sp_axis="sp")
    _, _, loss = step(params, opt_state, jax.device_put(toks, dsh),
                      jax.device_put(tgts, dsh))
    assert float(loss) == pytest.approx(ref, rel=1e-5)


def test_rope_with_pipeline_schedules():
    """RoPE (and GQA) through both pipeline schedules: the blocks rotate
    from position 0 per microbatch, matching the single-device step."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distkeras_tpu.parallel.lm import shift_targets
    from distkeras_tpu.parallel.mesh import create_nd_mesh
    from distkeras_tpu.parallel.pipeline import (
        make_pp_train_step, pp_state_shardings, split_block_params)

    mesh = create_nd_mesh((2, 2), ("dp", "pp"))
    spec = small_lm_spec(vocab_size=VOCAB, model_dim=D, num_heads=2,
                         num_kv_heads=1, num_layers=2, max_seq_len=16,
                         positional="rope")
    spec.config["compute_dtype"] = "float32"
    model = Model.init(spec, seed=0)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, VOCAB, size=(8, 16)).astype(np.int32)
    targets = shift_targets(tokens)

    module = spec.build()

    def loss_fn(params, tok, tgt):
        import optax as _o
        logits = module.apply({"params": params}, tok)
        ce = _o.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), tgt)
        return ce[:, :-1].mean()

    loss_ref = float(loss_fn(model.params, jnp.asarray(tokens),
                             jnp.asarray(targets)))

    dsh = NamedSharding(mesh, P("dp"))
    for schedule in ("gpipe", "1f1b"):
        outer, blocks = split_block_params(
            jax.tree.map(jnp.array, model.params))
        step = make_pp_train_step(spec, opt, mesh, num_microbatches=2,
                                  schedule=schedule)
        psh, osh = pp_state_shardings(mesh, opt, outer, blocks)
        params = jax.device_put((outer, blocks), psh)
        opt_state = jax.device_put(opt.init((outer, blocks)), osh)
        _, _, loss = step(params, opt_state, jax.device_put(tokens, dsh),
                          jax.device_put(targets, dsh))
        assert float(loss) == pytest.approx(loss_ref, rel=1e-4), schedule


def test_rope_generates_past_max_seq_len():
    """No positional table => max_seq_len is NOT a generation bound for
    rope models (only the cache size is): generating past it works, and
    the decode prefix is unchanged by the longer run.  A learned-table
    model with the same shape still refuses."""
    spec = _rope_spec(max_seq_len=16)
    model = Model.init(spec, seed=5)
    prompt = jnp.asarray([[5, 17, 3]], jnp.int32)
    long = np.asarray(make_generate_fn(spec, 24)(model.params, prompt))
    short = np.asarray(make_generate_fn(spec, 8)(model.params, prompt))
    assert long.shape == (1, 24)
    np.testing.assert_array_equal(long[:, :8], short)

    learned = small_lm_spec(vocab_size=VOCAB, model_dim=D, num_heads=H,
                            num_layers=LAYERS, max_seq_len=16)
    lmodel = Model.init(learned, seed=5)
    with pytest.raises(ValueError, match="positional table"):
        make_generate_fn(learned, 24)(lmodel.params, prompt)


def test_fused_step_refuses_rope():
    from distkeras_tpu.ops.decode_step import fused_step_supported, resolve_step_impl

    spec = _rope_spec(model_dim=128, num_heads=1)
    cfg = dict(spec.config)
    assert not fused_step_supported(cfg, 1, 256)
    assert resolve_step_impl(cfg, 1, 256, None) == "xla"
