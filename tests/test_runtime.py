"""Runtime layer tests: framed transport, PS hub semantics, async trainers.

Covers the reference's L3 (SURVEY.md §2.11–2.12) — here pickle-free and
with the genuinely-asynchronous trainer family on top."""

import socket
import threading

import numpy as np
import pytest

from distkeras_tpu.runtime import networking as net
from distkeras_tpu.runtime.parameter_server import (
    ADAGParameterServer,
    DeltaParameterServer,
    DynSGDParameterServer,
    PSClient,
)


# -- framing ------------------------------------------------------------------

def test_tensor_frame_roundtrip():
    arrays = [np.arange(6, dtype=np.float32).reshape(2, 3), np.ones((4,), np.float32)]
    payload = net.encode_tensors(net.ACTION_COMMIT, arrays)
    action, blobs = net.decode_tensors(payload)
    assert action == net.ACTION_COMMIT
    assert len(blobs) == 2
    np.testing.assert_array_equal(np.frombuffer(blobs[0], np.float32).reshape(2, 3), arrays[0])


def test_tensor_frame_trailing_bytes_rejected():
    payload = net.encode_tensors(net.ACTION_PULL, []) + b"junk"
    with pytest.raises(ValueError, match="trailing"):
        net.decode_tensors(payload)


def test_json_frames_over_socketpair():
    a, b = socket.socketpair()
    try:
        net.send_json(a, {"action": "submit", "job": "mnist", "n": 3})
        msg = net.recv_json(b)
        assert msg == {"action": "submit", "job": "mnist", "n": 3}
    finally:
        a.close()
        b.close()


# -- zero-copy flat framing (issue 3) -----------------------------------------

def _codec_templates():
    return [np.zeros((2, 3), np.float32), np.zeros((5,), np.float32)]


def test_flat_codec_wire_bytes_match_generic_encoder():
    """The codec's frame must be byte-identical to encode_tensors' — the
    C++ hub and generic peers parse one layout."""
    arrays = [np.arange(6, dtype=np.float32).reshape(2, 3),
              np.linspace(0, 1, 5).astype(np.float32)]
    codec = net.FlatFrameCodec(_codec_templates())
    a, b = socket.socketpair()
    try:
        codec.send(a, net.ACTION_COMMIT, arrays)
        frame = net._recv_exact(b, codec.frame_len)
        generic = net.encode_tensors(net.ACTION_COMMIT, arrays)
        assert frame == len(generic).to_bytes(8, "big") + generic
    finally:
        a.close()
        b.close()


def test_flat_codec_interops_both_directions():
    """codec -> generic decode AND generic send -> codec scatter-receive."""
    tmpl = _codec_templates()
    codec = net.FlatFrameCodec(tmpl)
    arrays = [np.full((2, 3), 2.5, np.float32), np.arange(5, dtype=np.float32)]
    a, b = socket.socketpair()
    try:
        codec.send(a, net.ACTION_WEIGHTS, arrays)
        action, got = net.recv_tensors(b, templates=tmpl)
        assert action == net.ACTION_WEIGHTS
        for g, want in zip(got, arrays):
            np.testing.assert_array_equal(g, want)

        net.send_tensors(a, net.ACTION_WEIGHTS, arrays)
        out = [np.empty_like(t) for t in tmpl]
        action = codec.recv_into(b, out)
        assert action == net.ACTION_WEIGHTS
        for g, want in zip(out, arrays):
            np.testing.assert_array_equal(g, want)
    finally:
        a.close()
        b.close()


def test_flat_codec_rejects_schema_mismatch():
    tmpl = _codec_templates()
    codec = net.FlatFrameCodec(tmpl)
    a, b = socket.socketpair()
    try:
        # wrong tensor count on the wire -> frame size mismatch
        net.send_tensors(a, net.ACTION_WEIGHTS, [np.zeros((2, 3), np.float32)])
        with pytest.raises(ValueError, match="does not match"):
            codec.recv_into(b, [np.empty_like(t) for t in tmpl])
        # wrong dtype/size at pack time
        with pytest.raises(ValueError, match="does not match"):
            codec.pack(net.ACTION_COMMIT,
                       [np.zeros((2, 3), np.float64), np.zeros((5,), np.float32)])
    finally:
        a.close()
        b.close()


def test_recv_tensors_decodes_into_preallocated_out():
    """Satellite: templates/out decode straight into caller arrays — the
    returned arrays ARE the preallocated ones, no intermediate copies."""
    tmpl = _codec_templates()
    arrays = [np.full((2, 3), 4.0, np.float32), np.arange(5, dtype=np.float32)]
    a, b = socket.socketpair()
    try:
        net.send_tensors(a, net.ACTION_WEIGHTS, arrays)
        pre = [np.zeros_like(t) for t in tmpl]
        action, got = net.recv_tensors(b, out=pre)
        assert action == net.ACTION_WEIGHTS
        assert got[0] is pre[0] and got[1] is pre[1]
        for g, want in zip(pre, arrays):
            np.testing.assert_array_equal(g, want)
        # the template-less control-plane path still returns raw uint8
        net.send_tensors(a, net.ACTION_COMMIT, [np.zeros(3, np.float32)])
        action, blobs = net.recv_tensors(b)
        assert action == net.ACTION_COMMIT and blobs[0].dtype == np.uint8
    finally:
        a.close()
        b.close()


def test_recv_frame_into_reuses_buffer_and_views():
    a, b = socket.socketpair()
    try:
        buf = bytearray()
        net.send_frame(a, b"x" * 32)
        mv = net.recv_frame_into(b, buf)
        assert bytes(mv) == b"x" * 32 and len(buf) == 32
        net.send_frame(a, b"y" * 8)  # smaller frame: buffer NOT shrunk
        mv = net.recv_frame_into(b, buf)
        assert bytes(mv) == b"y" * 8 and len(buf) == 32
    finally:
        a.close()
        b.close()


# -- parameter servers --------------------------------------------------------

def _weights():
    return [np.zeros((2, 2), np.float32), np.zeros((3,), np.float32)]


def test_delta_ps_pull_commit():
    ps = DeltaParameterServer(_weights())
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights()) as c:
            w = c.pull()
            assert all(np.all(x == 0) for x in w)
            c.commit([np.ones((2, 2), np.float32), 2 * np.ones((3,), np.float32)])
            w = c.pull()
            np.testing.assert_allclose(w[0], np.ones((2, 2)))
            np.testing.assert_allclose(w[1], 2 * np.ones((3,)))
        assert ps.num_updates == 1
    finally:
        ps.stop()


def test_adag_ps_normalizes_by_num_workers():
    ps = ADAGParameterServer(_weights(), num_workers=4)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights()) as c:
            c.commit([np.full((2, 2), 4.0, np.float32), np.full((3,), 8.0, np.float32)])
            w = c.pull()
            np.testing.assert_allclose(w[0], np.ones((2, 2)))
            np.testing.assert_allclose(w[1], 2 * np.ones((3,)))
    finally:
        ps.stop()


def test_dynsgd_staleness_scaling():
    """Worker B pulls, then A's commit lands first: B's commit has
    staleness 1 and is scaled by 1/2 (reference DynSGD rule)."""
    ps = DynSGDParameterServer(_weights())
    ps.start()
    try:
        a = PSClient("127.0.0.1", ps.port, templates=_weights())
        b = PSClient("127.0.0.1", ps.port, templates=_weights())
        a.pull()
        b.pull()
        one = [np.ones((2, 2), np.float32), np.ones((3,), np.float32)]
        a.commit(one)  # staleness 0 -> full
        b.commit(one)  # staleness 1 -> half
        w = a.pull()
        np.testing.assert_allclose(w[0], np.full((2, 2), 1.5))
        a.close()
        b.close()
    finally:
        ps.stop()


def test_concurrent_commits_all_land():
    ps = DeltaParameterServer([np.zeros((16,), np.float32)])
    ps.start()
    n_workers, n_commits = 8, 20

    def work(i):
        with PSClient("127.0.0.1", ps.port, templates=[np.zeros((16,), np.float32)]) as c:
            for _ in range(n_commits):
                c.pull()
                c.commit([np.ones((16,), np.float32)])

    try:
        threads = [threading.Thread(target=work, args=(i,)) for i in range(n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        np.testing.assert_allclose(ps.get_weights()[0], np.full((16,), n_workers * n_commits))
        assert ps.num_updates == n_workers * n_commits
    finally:
        ps.stop()


def test_client_size_mismatch_raises():
    ps = DeltaParameterServer(_weights())
    ps.start()
    try:
        c = PSClient("127.0.0.1", ps.port, templates=[np.zeros((5,), np.float32)])
        with pytest.raises((ValueError, ConnectionError)):
            c.pull()
        c.sock.close()
    finally:
        ps.stop()


def test_ps_stop_wakes_accept_thread_immediately():
    """stop() must shutdown() the listener (close() alone does not wake a
    blocked accept() on Linux): before the fix every hub stop burned the
    full 5s join timeout and leaked its accept thread."""
    import time as _time

    ps = DeltaParameterServer(_weights())
    ps.start()
    t0 = _time.monotonic()
    ps.stop()
    assert _time.monotonic() - t0 < 2.0, "stop() waited on the accept thread"
    assert not ps._accept_thread.is_alive()


def test_pipelined_client_coalesces_acks_and_prefetches():
    """The issue-3 hot-path schedule, driven by hand: prefetch pull k+1
    BEFORE commit k, consume replies lazily — every commit still lands,
    every prefetched pull observes the center WITHOUT the commit sent
    after it (self-staleness 1), and drain() leaves nothing in flight."""
    ps = DeltaParameterServer([np.zeros((4,), np.float32)])
    ps.start()
    tmpl = [np.zeros((4,), np.float32)]
    one = [np.ones((4,), np.float32)]
    try:
        with PSClient("127.0.0.1", ps.port, templates=tmpl) as c:
            w0 = c.pull()
            np.testing.assert_array_equal(w0[0], 0)
            for k in range(4):
                c.pull_nowait()        # prefetch (k+1) — predates commit k
                c.commit_nowait(one)   # fire-and-forget
                # deadlock-avoidance contract: the commit send claimed the
                # in-flight weights reply FIRST (the hub must be parked in
                # recv while the commit bytes travel), so no weights reply
                # remains pending once commit_nowait returns
                assert all(kind != net.ACTION_WEIGHTS
                           for kind, _ in c._pending)
                w = c.wait_weights()   # hands out the claimed pull
                # the prefetched snapshot misses THIS window's commit
                np.testing.assert_array_equal(w[0], np.full(4, float(k)))
            c.drain()
            assert len(c._pending) == 0
            np.testing.assert_array_equal(c.pull()[0], np.full(4, 4.0))
        assert ps.num_updates == 4
    finally:
        ps.stop()


def test_pipelined_pull_buffers_double_buffer():
    """wait_weights alternates between two landing buffers, so the pull
    being consumed survives the next prefetched receive (and exactly one
    more)."""
    ps = DeltaParameterServer([np.zeros((4,), np.float32)])
    ps.start()
    tmpl = [np.zeros((4,), np.float32)]
    try:
        with PSClient("127.0.0.1", ps.port, templates=tmpl) as c:
            w1 = c.pull()
            c.commit([np.ones((4,), np.float32)])
            w2 = c.pull()
            assert w1[0] is not w2[0]  # different landing buffers
            np.testing.assert_array_equal(w1[0], 0)  # older pull intact
            np.testing.assert_array_equal(w2[0], 1)
            c.commit([np.ones((4,), np.float32)])
            w3 = c.pull()  # reuses w1's buffer
            assert w3[0] is w1[0]
            np.testing.assert_array_equal(w3[0], 2)
    finally:
        ps.stop()


def test_ps_killed_mid_run_surfaces_clean_error_no_hang():
    """Fault-injection satellite: the hub dies while a worker is mid
    pull/commit traffic — PSClient must surface ConnectionError/OSError
    promptly (no hang on a half-read frame, no silent corruption)."""
    import time as _time

    ps = DeltaParameterServer([np.zeros((1 << 16,), np.float32)])
    ps.start()
    tmpl = [np.zeros((1 << 16,), np.float32)]
    c = PSClient("127.0.0.1", ps.port, templates=tmpl, timeout=10.0)
    c.pull()
    c.commit([np.ones((1 << 16,), np.float32)])  # connection is known-good
    stopper = threading.Thread(target=ps.stop)
    deadline = _time.monotonic() + 30.0
    stopper.start()
    try:
        with pytest.raises((ConnectionError, OSError, ValueError)):
            while _time.monotonic() < deadline:
                c.pull_nowait()
                c.commit_nowait([np.ones((1 << 16,), np.float32)])
                c.wait_weights()
        assert _time.monotonic() < deadline, "client hung on a dead hub"
    finally:
        stopper.join()
        c.sock.close()
    # the center survived to the last APPLIED commit — an interrupted
    # frame must never half-apply
    applied = ps.get_weights()[0]
    assert float(applied[0]) == float(applied[-1])
    assert float(applied[0]) == ps.num_updates


# -- async trainers -----------------------------------------------------------

@pytest.mark.parametrize("trainer_name", ["AsyncDOWNPOUR", "AsyncADAG", "AsyncAEASGD", "AsyncDynSGD"])
def test_async_trainers_learn(trainer_name, toy_dataset):
    import distkeras_tpu as dk
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.predictors import ModelPredictor
    from distkeras_tpu.data.transformers import LabelIndexTransformer

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2}, input_shape=(8,))
    cls = getattr(dk, trainer_name)
    kwargs = dict(loss="categorical_crossentropy", batch_size=16, num_epoch=2,
                  num_workers=4, communication_window=4, learning_rate=0.05, seed=0)
    if trainer_name in ("AsyncAEASGD",):
        kwargs["rho"] = 2.0
    trainer = cls(Model.init(spec, seed=0), **kwargs)
    model = trainer.train(toy_dataset)
    assert trainer.parameter_server.num_updates > 0
    ds = ModelPredictor(model, features_col="features").predict(toy_dataset)
    ds = LabelIndexTransformer().transform(ds)
    acc = AccuracyEvaluator(prediction_col="prediction_index", label_col="label_index").evaluate(ds)
    assert acc > 0.9, f"{trainer_name} accuracy {acc}"
    assert len(trainer.history) > 0


def test_async_checkpoint_snapshots_and_resume(toy_dataset, tmp_path):
    """Async checkpoint story (round-1 weak #7): periodic center snapshots
    + resume-from-latest-center."""
    import numpy as np

    from distkeras_tpu.checkpoint import Checkpointer
    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.runtime.async_trainer import AsyncDOWNPOUR

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    ck = Checkpointer(str(tmp_path / "async-ck"), keep=3)
    t1 = AsyncDOWNPOUR(spec, num_workers=2, communication_window=2,
                       batch_size=16, num_epoch=2, learning_rate=0.05,
                       checkpoint_interval=0.2)
    m1 = t1.train(toy_dataset, checkpointer=ck)
    # at least the final snapshot exists, and it equals the returned center
    assert ck.latest_step() is not None
    restored = ck.restore({"params": m1.params})
    for a, b in zip(jax_leaves(restored["params"]), jax_leaves(m1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    # resume: a fresh trainer with the same checkpointer starts FROM the
    # snapshot center, not from init
    t2 = AsyncDOWNPOUR(spec, num_workers=2, communication_window=2,
                       batch_size=16, num_epoch=1, seed=123)
    assert t2._maybe_restore(ck) is True
    for a, b in zip(jax_leaves(t2.model.params), jax_leaves(m1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    # and training from the restored center still runs end to end
    m2 = t2.train(toy_dataset, checkpointer=ck)
    assert len(t2.history) > 0


def jax_leaves(tree):
    import jax

    return jax.tree.leaves(tree)


def test_fault_injection_continue_and_raise(toy_dataset):
    """Failure-policy test (SURVEY §5 failure detection): a deterministically
    killed worker either fails the run (default) or is tolerated while the
    survivors finish ('continue')."""
    import pytest as _pytest

    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.runtime.async_trainer import AsyncDOWNPOUR

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))

    def kill_worker_1(idx, window):
        if idx == 1 and window == 1:
            raise RuntimeError("injected fault: worker 1 dies at window 1")

    common = dict(num_workers=2, communication_window=2, batch_size=16,
                  num_epoch=2, learning_rate=0.05, fault_hook=kill_worker_1)

    t = AsyncDOWNPOUR(spec, **common)
    with _pytest.raises(RuntimeError, match="injected fault"):
        t.train(toy_dataset)

    t2 = AsyncDOWNPOUR(spec, on_worker_failure="continue", **common)
    model = t2.train(toy_dataset)  # survivors finish, center returned
    assert len(t2.worker_errors) == 1
    assert "injected fault" in str(t2.worker_errors[0])
    assert len(t2.history) > 0  # worker 0 trained through both epochs
    assert model.predict(toy_dataset["features"][:8]).shape == (8, 2)


def test_q_blob_roundtrip_and_error_feedback():
    """quantize/dequantize inverts within scale/2 per element, and the
    client-side error-feedback accumulator makes the SUM of dequantized
    commits track the sum of true deltas (compression is unbiased over
    time — the property that lets int8 commits train)."""
    from distkeras_tpu.runtime.networking import (dequantize_q_blob,
                                                  quantize_q_blob)

    rng = np.random.default_rng(0)
    d = rng.normal(size=(64,)).astype(np.float32)
    blob, residual = quantize_q_blob(d)
    back = dequantize_q_blob(blob, 64)
    scale = np.frombuffer(blob[:4], ">f4")[0]
    assert np.abs(back - d).max() <= scale / 2 + 1e-7
    np.testing.assert_allclose(back + residual, d, rtol=0, atol=1e-6)

    # zero delta: scale stays 1.0, nothing divides by zero
    zb, zr = quantize_q_blob(np.zeros(8, np.float32))
    assert np.all(dequantize_q_blob(zb, 8) == 0) and np.all(zr == 0)

    # error feedback across a stream of commits
    true_sum = np.zeros(64, np.float32)
    wire_sum = np.zeros(64, np.float32)
    carry = np.zeros(64, np.float32)
    for step in range(50):
        d = rng.normal(size=(64,)).astype(np.float32) * 0.01
        true_sum += d
        blob, carry = quantize_q_blob(d + carry)
        wire_sum += dequantize_q_blob(blob, 64)
    # the residual is all that separates the sums, and it is bounded by
    # one quantum — NOT growing with the number of commits
    np.testing.assert_allclose(wire_sum, true_sum, atol=5e-3)


def test_int8_commits_land_like_f32_commits():
    """An int8-compressed commit of exactly-representable deltas must move
    the Python hub's center exactly like the f32 commit (ADAG scaling
    applies AFTER dequantization, on the hub)."""
    ps = ADAGParameterServer(_weights(), num_workers=4)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      compress="int8") as c:
            # max|d| = 127 makes the scale exactly 1.0: quantization is
            # lossless here, isolating the wire path from rounding
            c.commit([np.full((2, 2), 127.0, np.float32),
                      np.full((3,), 127.0, np.float32)])
            w = c.pull()
            np.testing.assert_allclose(w[0], np.full((2, 2), 127.0 / 4))
            np.testing.assert_allclose(w[1], np.full((3,), 127.0 / 4))
        assert ps.num_updates == 1
    finally:
        ps.stop()


def test_compressed_async_trainer_learns(toy_dataset):
    """AsyncDOWNPOUR with compress_commits='int8' reaches the same toy
    accuracy as uncompressed — error feedback keeps training unbiased."""
    import distkeras_tpu as dk
    from distkeras_tpu.data.transformers import LabelIndexTransformer
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.predictors import ModelPredictor

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    trainer = dk.AsyncDOWNPOUR(
        Model.init(spec, seed=0), loss="categorical_crossentropy",
        batch_size=16, num_epoch=2, num_workers=4, communication_window=4,
        learning_rate=0.05, seed=0, compress_commits="int8")
    model = trainer.train(toy_dataset)
    assert trainer.parameter_server.num_updates > 0
    ds = ModelPredictor(model, features_col="features").predict(toy_dataset)
    ds = LabelIndexTransformer().transform(ds)
    acc = AccuracyEvaluator(prediction_col="prediction_index",
                            label_col="label_index").evaluate(ds)
    assert acc > 0.9, f"int8-commit training underperformed: {acc}"
