"""Sequential (arbitrary layer-stack) models: the Keras-Sequential parity
surface — construction, serialization, training, and error reporting."""

import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.base import Model
from distkeras_tpu.models.sequential import (activation, avg_pool2d, conv2d,
                                             dense, dropout, embed, flatten,
                                             global_avg_pool, layer_norm,
                                             max_pool2d, sequential_spec)
from distkeras_tpu.trainers import DOWNPOUR, SingleTrainer


def test_cnn_stack_shapes_match_hand_built():
    spec = sequential_spec(
        [conv2d(8, 3, activation="relu"), max_pool2d(2),
         conv2d(16, 3, activation="relu"), avg_pool2d(2),
         flatten(), dense(32, "relu"), layer_norm(), dense(10)],
        input_shape=(28, 28, 1))
    m = Model.init(spec, seed=0)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 28, 28, 1)), jnp.float32)
    assert m.apply(x).shape == (4, 10)


def test_serialize_roundtrip_rebuilds_identical_model():
    spec = sequential_spec(
        [embed(vocab_size=30, dim=8), global_avg_pool(), dense(4)],
        input_shape=(12,), input_dtype="int32")
    m = Model.init(spec, seed=3)
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 30, (5, 12)))
    m2 = Model.deserialize(m.serialize())
    np.testing.assert_array_equal(np.asarray(m2.apply(toks)), np.asarray(m.apply(toks)))


def test_sequential_trains_with_single_and_distributed_trainers():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8, 8, 1)).astype(np.float32)
    labels = (x.mean(axis=(1, 2, 3)) > 0).astype(np.int64)
    onehot = np.eye(2, dtype=np.float32)[labels]
    ds = Dataset({"features": x, "label": onehot})
    spec = sequential_spec(
        [conv2d(4, 3, activation="relu"), flatten(), dense(16, "relu"), dense(2)],
        input_shape=(8, 8, 1))

    tr = SingleTrainer(spec, batch_size=32, num_epoch=10, learning_rate=0.05)
    model = tr.train(ds)
    pred = np.argmax(np.asarray(model.apply(jnp.asarray(x))), axis=1)
    assert (pred == labels).mean() > 0.9

    tr2 = DOWNPOUR(spec, num_workers=8, batch_size=16, num_epoch=2,
                   communication_window=2, learning_rate=0.05)
    model2 = tr2.train(ds)
    assert model2.apply(jnp.asarray(x[:4])).shape == (4, 2)
    assert np.isfinite(tr2.history).all()


def test_activation_and_kind_errors_name_the_layer():
    bad = sequential_spec([dense(4), {"kind": "wat"}], input_shape=(3,))
    with pytest.raises(ValueError, match="layer 1: unknown kind 'wat'"):
        Model.init(bad, seed=0)
    with pytest.raises(ValueError, match="unknown activation"):
        Model.init(sequential_spec([dense(4, "swishh")], input_shape=(3,)), seed=0)
    with pytest.raises(ValueError, match="layer_norm"):
        Model.init(sequential_spec([{"kind": "batch_norm"}], input_shape=(3,)), seed=0)
    with pytest.raises(ValueError, match="at least one layer"):
        Model.init(sequential_spec([], input_shape=(3,)), seed=0)


def test_dropout_inference_deterministic_training_stochastic():
    import jax

    spec = sequential_spec([dense(32, "relu"), dropout(0.5), dense(2)],
                           input_shape=(3,))
    m = Model.init(spec, seed=0)
    x = jnp.ones((2, 3))
    # inference path: dropout off, bit-reproducible
    np.testing.assert_array_equal(np.asarray(m.apply(x)), np.asarray(m.apply(x)))
    # train path: two keys -> two masks -> different outputs
    train_apply = spec.train_apply_fn()
    a = np.asarray(train_apply(m.params, x, jax.random.PRNGKey(0)))
    b = np.asarray(train_apply(m.params, x, jax.random.PRNGKey(1)))
    assert np.abs(a - b).max() > 0
    # same key -> same mask
    np.testing.assert_array_equal(
        a, np.asarray(train_apply(m.params, x, jax.random.PRNGKey(0))))
    assert spec.needs_rng
    assert not sequential_spec([dense(4)], input_shape=(3,)).needs_rng
    assert not sequential_spec([dense(4), dropout(0.0)],
                               input_shape=(3,)).needs_rng


def test_typoed_layer_keys_fail_loudly():
    bad = sequential_spec(
        [{"kind": "conv2d", "filters": 8, "kernel_size": 3, "stride": 2}],
        input_shape=(8, 8, 1))
    with pytest.raises(ValueError, match=r"layer 0: unknown key\(s\) \['stride'\]"):
        Model.init(bad, seed=0)


def test_tuple_layer_params_survive_serialize_roundtrip():
    spec = sequential_spec([conv2d(8, (3, 3)), flatten(), dense(4)],
                           input_shape=(8, 8, 1))
    m = Model.init(spec, seed=0)
    m2 = Model.deserialize(m.serialize())
    assert m2.spec == m.spec


def test_activation_layer_and_pool_defaults():
    spec = sequential_spec(
        [conv2d(4, [3, 3], strides=[1, 1], padding="VALID"),
         activation("tanh"), max_pool2d([2, 2]), flatten(), dense(3)],
        input_shape=(10, 10, 2))
    m = Model.init(spec, seed=0)
    out = m.apply(jnp.zeros((2, 10, 10, 2)))
    assert out.shape == (2, 3)
