"""Sharded parameter-server hub (ISSUE 6): shard plan properties, wire
compatibility, the striped client, per-shard faults/telemetry, and the
1-shard == unsharded trajectory-parity matrix.

The acceptance contract: ``num_shards=1`` is byte-identical to today's
single-hub wire, and an N-shard run at 1 worker is bit-identical to the
1-shard trajectory — partitioning the center must change WHERE the bytes
land, never what they compute.
"""

import os

import numpy as np
import pytest

from distkeras_tpu import observability as obs
from distkeras_tpu.runtime import networking as net
from distkeras_tpu.runtime.networking import FlatFrameCodec
from distkeras_tpu.runtime.parameter_server import (
    ADAGParameterServer,
    DeltaParameterServer,
    InprocPSClient,
    PSClient,
    ShardedParameterServer,
    ShardedPSClient,
    shard_plan,
)


def _templates():
    return [np.zeros((6, 4), np.float32), np.zeros((17,), np.float32),
            np.zeros((3, 3), np.float32), np.zeros((11,), np.float32),
            np.zeros((2,), np.float32), np.zeros((29,), np.float32)]


# -- shard plan properties -----------------------------------------------------

def test_shard_plan_deterministic_and_identity_at_one_shard():
    t = _templates()
    p1, p2 = shard_plan(t, 3), shard_plan(t, 3)
    assert p1.assignments == p2.assignments
    assert shard_plan(t, 1).assignments == (tuple(range(len(t))),)
    # every leaf assigned exactly once, each shard ascending
    seen = sorted(i for idxs in p1.assignments for i in idxs)
    assert seen == list(range(len(t)))
    for idxs in p1.assignments:
        assert list(idxs) == sorted(idxs)


def test_shard_plan_stable_under_leaf_reorder():
    """The assignment is a function of each leaf's (nbytes, dtype, shape)
    identity, not its position: permuting the template list maps every
    leaf to the same shard."""
    t = _templates()  # all layouts distinct
    base = shard_plan(t, 3)
    shard_of = {}
    for s, idxs in enumerate(base.assignments):
        for i in idxs:
            shard_of[i] = s
    rng = np.random.default_rng(7)
    for _ in range(5):
        perm = list(rng.permutation(len(t)))
        permuted = shard_plan([t[i] for i in perm], 3)
        for s, idxs in enumerate(permuted.assignments):
            for j in idxs:
                assert shard_of[perm[j]] == s, (
                    f"leaf {perm[j]} moved shard under permutation {perm}")


def test_shard_plan_balance_bound():
    """LPT guarantee: the heaviest shard exceeds the lightest by at most
    one leaf's bytes — for random size mixes, not just the fixture."""
    rng = np.random.default_rng(0)
    for trial in range(10):
        sizes = rng.integers(1, 2000, size=rng.integers(4, 40))
        t = [np.zeros(int(sz), np.float32) for sz in sizes]
        for shards in (2, 3, 4):
            if shards > len(t):
                continue
            plan = shard_plan(t, shards)
            assert sum(plan.shard_bytes) == sum(a.nbytes for a in t)
            spread = max(plan.shard_bytes) - min(plan.shard_bytes)
            assert spread <= max(a.nbytes for a in t), (
                f"trial {trial}, {shards} shards: spread {spread}")


def test_shard_plan_rejects_bad_shard_counts():
    t = _templates()
    with pytest.raises(ValueError, match="num_shards"):
        shard_plan(t, 0)
    with pytest.raises(ValueError, match="exceeds"):
        shard_plan(t, len(t) + 1)


def test_shard_plan_split_assemble_roundtrip_by_reference():
    t = _templates()
    plan = shard_plan(t, 3)
    arrays = [np.full(a.shape, i, np.float32) for i, a in enumerate(t)]
    back = plan.assemble(plan.split(arrays))
    assert all(b is a for b, a in zip(back, arrays))  # zero-copy contract


# -- wire compatibility (the num_shards=1 acceptance criterion) ----------------

def test_one_shard_codec_frames_byte_identical_to_unsharded():
    """A 1-shard plan's only shard carries all leaves in template order,
    so its codec's packed frame is byte-for-byte today's wire — against
    both the flat codec and the generic encoder."""
    t = _templates()
    plan = shard_plan(t, 1)
    payload = [np.full(a.shape, 0.25 * (i + 1), np.float32)
               for i, a in enumerate(t)]
    unsharded = FlatFrameCodec(t)
    unsharded.pack(net.ACTION_COMMIT, payload)
    shard0 = FlatFrameCodec([t[i] for i in plan.assignments[0]])
    shard0.pack(net.ACTION_COMMIT, [payload[i] for i in plan.assignments[0]])
    assert bytes(unsharded._tx) == bytes(shard0._tx)
    generic = net.encode_tensors(net.ACTION_COMMIT, payload)
    assert bytes(unsharded._tx)[8:] == generic


def test_trainer_num_shards_one_uses_plain_hub_and_client(toy_dataset):
    """num_shards=1 (the default) short-circuits the sharded machinery
    entirely: the trainer owns a plain hub, not the facade — today's code
    path, byte-identical by construction."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    tr = dk.AsyncADAG(Model.init(spec, seed=0),
                      loss="categorical_crossentropy", batch_size=16,
                      num_epoch=1, num_workers=1, communication_window=4,
                      learning_rate=0.05, seed=0)
    tr.train(toy_dataset)
    assert isinstance(tr.parameter_server, ADAGParameterServer)
    assert tr._shard_plan is None


# -- facade + striped client ---------------------------------------------------

def _start_sharded(templates, num_shards, cls=DeltaParameterServer, **hub_kw):
    plan = shard_plan(templates, num_shards)
    ps = ShardedParameterServer(
        templates, plan,
        lambda w, sid: cls(w, shard_id=sid, idle_timeout=None, **hub_kw))
    ps.start()
    return ps, plan


def test_facade_lifecycle_weights_and_direct_transport():
    t = [np.full(a.shape, 1.0, np.float32) for a in _templates()]
    ps, plan = _start_sharded(t, 3)
    try:
        assert len(ps.ports) == 3 and ps.port == ps.ports[0]
        got = ps.get_weights()
        assert [g.shape for g in got] == [a.shape for a in t]
        assert all(np.all(g == 1.0) for g in got)
        # direct pair: tuple clocks ride through opaque to the client
        weights, clocks = ps.pull_direct()
        assert isinstance(clocks, tuple) and len(clocks) == 3
        ps.commit_direct([np.full(a.shape, 0.5, np.float32) for a in t], clocks)
        assert ps.num_updates == 1
        assert all(np.allclose(g, 1.5) for g in ps.get_weights())
        # int clock broadcasts (the inproc client's pre-pull default)
        ps.commit_direct([np.full(a.shape, 0.5, np.float32) for a in t], 0)
        assert ps.num_updates == 2
        # InprocPSClient works against the facade unchanged
        client = InprocPSClient(ps, templates=t)
        pulled = client.pull()
        assert all(np.allclose(g, 2.0) for g in pulled)
        client.commit([np.full(a.shape, -1.0, np.float32) for a in t])
        assert all(np.allclose(g, 1.0) for g in ps.get_weights())
    finally:
        ps.stop()


def test_parallel_direct_pool_matches_sequential_walk():
    """The per-shard worker pool (ISSUE 18) changes WHERE each stripe
    runs, not what it computes: parallel_direct=True fans the stripes out
    to one long-lived dk-shard-worker thread per shard, and the results
    stay bit-identical to the sequential walk because the shards are
    disjoint state."""
    import threading

    t = _templates()
    rng = np.random.default_rng(7)
    deltas = [[rng.normal(size=a.shape).astype(np.float32) for a in t]
              for _ in range(3)]

    def run(parallel):
        plan = shard_plan(t, 3)
        ps = ShardedParameterServer(
            t, plan,
            lambda w, sid: DeltaParameterServer(w, shard_id=sid,
                                                idle_timeout=None),
            parallel_direct=parallel)
        ps.start()
        try:
            if parallel:
                assert ps._pool is not None and ps._pool.running
                names = {th.name for th in threading.enumerate()}
                assert {f"dk-shard-worker-{i}" for i in range(3)} <= names
            else:
                assert ps._pool is None
            for d in deltas:
                _, clocks = ps.pull_direct()
                ps.commit_direct(d, clocks)
            assert ps.num_updates == len(deltas)
            return [w.copy() for w in ps.get_weights()]
        finally:
            ps.stop()

    pooled, sequential = run(True), run(False)
    for a, b in zip(pooled, sequential):
        np.testing.assert_array_equal(a, b)
    # the pool threads are reaped on stop()
    assert not any(th.name.startswith("dk-shard-worker")
                   for th in threading.enumerate())


def test_striped_client_pull_commit_and_int8_parity():
    """The striped socket client lands values identical to an unsharded
    client over the same math — including int8 error-feedback commits,
    whose residual chain is per leaf and therefore shard-invariant."""
    t = _templates()
    rng = np.random.default_rng(3)
    deltas = [[rng.normal(size=a.shape).astype(np.float32) for a in t]
              for _ in range(4)]

    def run(num_shards, compress):
        ps, plan = _start_sharded(t, num_shards)
        try:
            if num_shards == 1:
                client = PSClient("127.0.0.1", ps.ports[0], t,
                                  compress=compress)
            else:
                client = ShardedPSClient([("127.0.0.1", p) for p in ps.ports],
                                         t, plan, compress=compress)
            with client:
                for d in deltas:
                    client.commit(d)
                final = [w.copy() for w in client.pull()]
            return final
        finally:
            ps.stop()

    for compress in (None, "int8"):
        one = run(1, compress)
        three = run(3, compress)
        for a, b in zip(one, three):
            np.testing.assert_array_equal(a, b)


def test_striped_client_rejects_address_plan_mismatch():
    t = _templates()
    plan = shard_plan(t, 3)
    with pytest.raises(ValueError, match="shard addresses"):
        ShardedPSClient([("127.0.0.1", 1)], t, plan)


def test_facade_live_workers_is_min_across_shards():
    """A worker counts as fleet-live only while ALL its shard connections
    do: membership is per shard, and the facade reports the min."""
    t = _templates()
    ps, plan = _start_sharded(t, 2)
    try:
        assert ps.live_workers() == 0
        client = ShardedPSClient([("127.0.0.1", p) for p in ps.ports], t, plan)
        with client:
            client.commit([np.zeros(a.shape, np.float32) for a in t])
            assert ps.live_workers() == 1
            # sever ONE shard connection: the worker drops out of the
            # fleet-live count even though the other shard still sees it
            import time

            client.shards[1].sock.close()
            deadline = time.monotonic() + 5.0
            while ps.live_workers() != 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert ps.live_workers() == 0
            assert ps.shards[0].live_workers() == 1
    finally:
        ps.stop()


# -- satellite: per-shard socket-buffer sizing ---------------------------------

def test_socket_buffers_sized_from_per_shard_frames():
    """Each shard hub (and each per-shard client codec) sizes its kernel
    buffers from ITS tensor subset: N shard connections cost about one
    model of buffer hint in total, not N models."""
    t = [np.zeros(65536, np.float32) for _ in range(4)]  # 256 KiB leaves
    full_frame = net.tensor_frame_len(t)
    ps, plan = _start_sharded(t, 4)
    try:
        for sid, hub in enumerate(ps.shards):
            shard_frame = net.tensor_frame_len(
                [t[i] for i in plan.assignments[sid]])
            assert hub._frame_bytes == shard_frame
            assert hub._frame_bytes < full_frame
        # the sum of per-shard hints is the full frame plus one 13-byte
        # header+count per extra shard — not 4x the model
        assert sum(h._frame_bytes for h in ps.shards) == full_frame + 3 * 13
        client = ShardedPSClient([("127.0.0.1", p) for p in ps.ports], t, plan)
        with client:
            for sid, sc in enumerate(client.shards):
                assert sc._codec.frame_len == net.tensor_frame_len(
                    [t[i] for i in plan.assignments[sid]])
    finally:
        ps.stop()


# -- per-shard telemetry + fleet attribution (satellite) -----------------------

def test_per_shard_telemetry_labels_and_fleet_report():
    t = _templates()
    obs.reset()
    # spans from earlier tests' runs would inflate the fleet report's
    # commit counts — this test owns the ring
    obs.TRACER.clear()
    obs.enable()
    try:
        ps, plan = _start_sharded(t, 2)
        try:
            client = ShardedPSClient([("127.0.0.1", p) for p in ps.ports],
                                     t, plan)
            with client:
                for _ in range(3):
                    client.commit([np.zeros(a.shape, np.float32) for a in t])
                client.pull()
            snap = obs.snapshot()
            counters = snap["counters"]
            # hub side: per-shard series, no unlabeled double count (the
            # unlabeled series may exist zeroed from earlier tests'
            # instruments — reset() zeroes, it does not unregister)
            for sid in (0, 1):
                assert counters[f'ps_commits_total{{shard="{sid}"}}'] == 3.0
            assert counters.get("ps_commits_total", 0.0) == 0.0
            # client side: per-shard commit bytes sum to the stripe total
            stripe = sum(
                counters[f'ps.commit_bytes{{shard="{sid}"}}']
                for sid in (0, 1))
            expected = 3 * sum(
                net.tensor_frame_len([t[i] for i in idxs])
                for idxs in plan.assignments)
            assert stripe == expected
            assert 'ps_commit_staleness{shard="0"}' in snap["histograms"]
        finally:
            ps.stop()
        # fleet_report: logical commits (no double count) + shard table
        from distkeras_tpu.observability.distributed import fleet_report

        report = fleet_report(events=obs.TRACER.events())
        assert report["total_commits"] == 3
        assert set(report["shards"]) == {"0", "1"}
        assert report["shards"]["0"]["commits"] == 3
        assert report["slowest_shard"] in ("0", "1")
    finally:
        obs.disable()
        obs.reset()


# -- per-shard chaos (satellite: ChaosProxy shard faults) ----------------------

def test_sharded_chaos_proxy_severs_one_stripe_and_client_recovers():
    from distkeras_tpu.runtime.faults import Fault, FaultPlan, ShardedChaosProxy

    t = _templates()
    ps, plan = _start_sharded(t, 2)
    try:
        fault_plan = FaultPlan([Fault(conn=0, frame=1, direction="s2c",
                                      kind="sever", shard=1)])
        with ShardedChaosProxy([("127.0.0.1", p) for p in ps.ports],
                               plan=fault_plan) as proxy:
            client = ShardedPSClient(
                [("127.0.0.1", p) for p in proxy.ports], t, plan,
                max_reconnects=3, reconnect_backoff=0.02)
            with client:
                for _ in range(4):
                    client.commit([np.full(a.shape, 0.5, np.float32)
                                   for a in t])
                final = [w.copy() for w in client.pull()]
            fired = proxy.faults_fired
            assert [f.shard for f in fired] == [1]
            assert proxy.proxies[0].faults_fired == []
            # shard 1's severed stripe dropped at most the in-flight
            # commit; shard 0 saw all four.  Recovery means the final
            # center is consistent per shard and the client survived
            assert client.shards[1].reconnects_used >= 1
            assert ps.shards[0].num_updates == 4
            assert ps.shards[1].num_updates >= 3
            for idxs, hub in zip(plan.assignments, ps.shards):
                n = hub.num_updates
                for i in idxs:
                    np.testing.assert_allclose(final[i], 0.5 * n, rtol=1e-6)
    finally:
        ps.stop()


# -- coordinated per-shard snapshots (restored as a unit) ----------------------

def test_sharded_snapshot_set_restores_as_a_unit(tmp_path):
    t = [np.full(a.shape, 1.0, np.float32) for a in _templates()]

    def factory_for(base):
        def factory(w, sid):
            return DeltaParameterServer(
                w, shard_id=sid, idle_timeout=None,
                snapshot_dir=os.path.join(base, f"shard-{sid:02d}"),
                snapshot_interval=3600.0)
        return factory

    plan = shard_plan(t, 2)
    ps = ShardedParameterServer(t, plan, factory_for(str(tmp_path)))
    ps.start()
    try:
        ps.commit_direct([np.full(a.shape, 0.5, np.float32) for a in t], 0)
        for hub in ps.shards:
            hub.snapshotter.save_now()
        expected = [w.copy() for w in ps.get_weights()]
    finally:
        ps.kill()  # crash semantics: recovery must come from the snapshots

    def restore_factory(w, sid):
        return DeltaParameterServer(
            w, shard_id=sid, idle_timeout=None,
            snapshot_dir=os.path.join(str(tmp_path), f"shard-{sid:02d}"),
            snapshot_interval=3600.0, restore=True)

    fresh = ShardedParameterServer(
        [np.zeros(a.shape, np.float32) for a in t], plan, restore_factory)
    fresh.start()
    try:
        got = fresh.get_weights()
        for a, b in zip(expected, got):
            np.testing.assert_array_equal(a, b)
        # per-shard clock fences armed at each shard's restored clock
        for hub in fresh.shards:
            assert hub._clock_fence == hub._clock == 1
    finally:
        fresh.stop()


# -- standalone per-shard hubs (launcher + worker-only striping) ---------------

def test_worker_only_mode_against_standalone_shard_hubs(toy_dataset):
    """The multi-host sharded topology end to end in one process: one
    start_parameter_server(shard_index=i) hub per shard (each derives the
    SAME deterministic plan from the same model), and a worker-only
    trainer striping against their addresses."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.runtime.launcher import start_parameter_server

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    model = Model.init(spec, seed=0)
    hubs = [start_parameter_server(model, mode="adag", num_workers=1,
                                   host="127.0.0.1", port=0,
                                   idle_timeout=None,
                                   num_shards=2, shard_index=i)
            for i in range(2)]
    try:
        from distkeras_tpu.utils import flatten_weights

        flat, _ = flatten_weights(model.params)
        plan = shard_plan([np.asarray(w, np.float32) for w in flat], 2)
        for sid, hub in enumerate(hubs):
            assert hub.shard_id == sid
            assert len(hub.center) == len(plan.assignments[sid])
        tr = dk.AsyncADAG(model, loss="categorical_crossentropy",
                          batch_size=16, num_epoch=1, num_workers=1,
                          communication_window=4, learning_rate=0.05, seed=0,
                          ps_address=[("127.0.0.1", h.port) for h in hubs])
        assert tr.num_shards == 2  # inferred from the address list
        trained = tr.train(toy_dataset)
        assert len(tr.history) > 0
        assert sum(h.num_updates for h in hubs) // 2 == len(tr.history)
        assert trained.predict(toy_dataset["features"][:4]).shape == (4, 2)
    finally:
        for h in hubs:
            h.stop()


def test_worker_only_address_count_must_match_num_shards():
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import ModelSpec

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    with pytest.raises(ValueError, match="per shard"):
        dk.AsyncADAG(spec, ps_address=[("a", 1), ("b", 2)], num_shards=3)


# -- the 1-shard == N-shard trajectory-parity matrix ---------------------------

_ALL_TRAINERS = ["AsyncDOWNPOUR", "AsyncADAG", "AsyncDynSGD", "AsyncAEASGD",
                 "AsyncEAMSGD"]
_REFERENCE_CACHE = {}


def _parity_dataset():
    rng = np.random.default_rng(11)
    n = 128
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, size=n)]
    from distkeras_tpu.data.dataset import Dataset

    return Dataset({"features": x, "label": y})


def _parity_run(trainer_name, *, num_shards, transport, hub):
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    kwargs = dict(loss="categorical_crossentropy", batch_size=16, num_epoch=1,
                  num_workers=1, communication_window=2, learning_rate=0.05,
                  seed=0, transport=transport, native_ps=(hub == "native"),
                  num_shards=num_shards)
    if trainer_name in ("AsyncAEASGD", "AsyncEAMSGD"):
        kwargs["rho"] = 2.0
    trainer = getattr(dk, trainer_name)(Model.init(spec, seed=0), **kwargs)
    model = trainer.train(_parity_dataset(), shuffle=False)
    return trainer.history, model


def _reference(trainer_name):
    """Unsharded reference trajectory, computed once per trainer (inproc/
    python — the cheapest transport; socket/native 1-shard parity with it
    is already pinned by test_transport.py / test_native_ps.py)."""
    if trainer_name not in _REFERENCE_CACHE:
        _REFERENCE_CACHE[trainer_name] = _parity_run(
            trainer_name, num_shards=1, transport="inproc", hub="python")
    return _REFERENCE_CACHE[trainer_name]


# tier-1 keeps ADAG's full 2x2 plus every trainer on the cheapest cell;
# the full suite (-m slow) runs the remaining 12 matrix cells
_MATRIX = []
for _name in _ALL_TRAINERS:
    for _transport in ("socket", "inproc"):
        for _hub in ("python", "native"):
            fast = (_name == "AsyncADAG"
                    or (_transport == "inproc" and _hub == "python"))
            _MATRIX.append(pytest.param(
                _name, _transport, _hub,
                marks=() if fast else pytest.mark.slow,
                id=f"{_name}-{_transport}-{_hub}"))


@pytest.mark.parametrize("trainer_name,transport,hub", _MATRIX)
def test_three_shard_run_bit_identical_to_unsharded(trainer_name, transport,
                                                    hub):
    """Sharding must not change the algorithm: at 1 worker, a 3-shard run
    is bit-identical to the unsharded reference trajectory for every
    Async* trainer, on both transports, against both hubs."""
    import jax

    if hub == "native":
        from distkeras_tpu.runtime.native import native_available

        if not native_available():
            pytest.skip("no C++ toolchain for the native hub")
    ref_history, ref_model = _reference(trainer_name)
    history, model = _parity_run(trainer_name, num_shards=3,
                                 transport=transport, hub=hub)
    assert history == ref_history, "window-loss trajectories diverged"
    for a, b in zip(jax.tree.leaves(ref_model.params),
                    jax.tree.leaves(model.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
