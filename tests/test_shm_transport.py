"""Zero-copy transport unit tests (ISSUE 18): the mmap SPSC frame ring,
the socket-shaped endpoint that rides two of them, the Z attach
handshake (codec + live client/hub negotiation, decline and fallback
paths), the batched hub receiver, and the recording-socket pin that the
quickack/batch-depth hub knobs leave the wire bytes untouched.
"""

import mmap
import os
import socket
import threading
import time

import numpy as np
import pytest

from distkeras_tpu import observability as obs
from distkeras_tpu.runtime import networking as net
from distkeras_tpu.runtime.parameter_server import (
    DeltaParameterServer, PSClient)


# -- the ring ------------------------------------------------------------------

def test_shm_ring_roundtrip_wraps_and_eofs(tmp_path):
    """Bytes written come back in order across many wraps of a tiny ring,
    and a closed producer reads as EOF (0) once drained — the recv_into
    contract the socket helpers depend on."""
    path = str(tmp_path / "ring")
    prod = net.ShmFrameRing.create(path, "producer", capacity=4096)
    cons = net.ShmFrameRing.open(path, "consumer")
    assert prod.capacity == 4096 and cons.capacity == 4096
    payload = bytes(range(256)) * 3  # 768 B: 40 rounds lap the ring ~7x
    buf = bytearray(1024)
    for _ in range(40):
        prod.write(payload, timeout=1.0)
        got = b""
        while len(got) < len(payload):
            n = cons.read_into(memoryview(buf), timeout=1.0)
            assert n > 0
            got += bytes(buf[:n])
        assert got == payload
    assert cons.pending == 0
    prod.close()
    assert cons.read_into(memoryview(buf), timeout=1.0) == 0  # EOF
    cons.close()


def test_shm_ring_capacity_rounds_up_to_power_of_two(tmp_path):
    ring = net.ShmFrameRing.create(str(tmp_path / "r"), "producer",
                                   capacity=5000)
    assert ring.capacity == 8192
    ring.close()


def test_shm_ring_open_rejects_junk_and_truncated_files(tmp_path):
    junk = tmp_path / "junk"
    junk.write_bytes(b"\x00" * (net.SHM_RING_HEADER + mmap.PAGESIZE))
    with pytest.raises(net.ProtocolError, match="magic"):
        net.ShmFrameRing.open(str(junk), "consumer")
    small = tmp_path / "small"
    small.write_bytes(b"not a ring")
    with pytest.raises(net.ProtocolError, match="too small"):
        net.ShmFrameRing.open(str(small), "consumer")
    with pytest.raises(ValueError, match="role"):
        net.ShmFrameRing.create(str(tmp_path / "r2"), "observer")


def test_shm_ring_full_parks_then_unblocks_and_times_out(tmp_path):
    """sendall semantics under backpressure: a full ring blocks the
    producer until the consumer drains, and a deadline overrun raises
    socket.timeout (so reconnect paths built for sockets keep working)."""
    path = str(tmp_path / "ring")
    prod = net.ShmFrameRing.create(path, "producer", capacity=4096)
    cons = net.ShmFrameRing.open(path, "consumer")
    prod.write(b"x" * 4096, timeout=1.0)  # exactly full
    with pytest.raises(socket.timeout):
        prod.write(b"y", timeout=0.05)

    def drain():
        time.sleep(0.05)
        buf = bytearray(2048)
        cons.read_into(memoryview(buf), timeout=1.0)

    t = threading.Thread(target=drain)
    t.start()
    prod.write(b"z" * 8, timeout=2.0)  # unblocks once the drain lands
    t.join()
    prod.close()
    cons.close()


def test_shm_ring_mark_closed_wakes_parked_reader(tmp_path):
    """The sever path: mark_closed raises BOTH flags, so a reader parked
    on an empty ring wakes with EOF instead of spinning forever."""
    path = str(tmp_path / "ring")
    prod = net.ShmFrameRing.create(path, "producer", capacity=4096)
    cons = net.ShmFrameRing.open(path, "consumer")
    result = {}

    def read():
        buf = bytearray(64)
        result["n"] = cons.read_into(memoryview(buf), timeout=5.0)

    t = threading.Thread(target=read)
    t.start()
    time.sleep(0.05)
    prod.mark_closed()
    t.join(timeout=2.0)
    assert not t.is_alive() and result["n"] == 0
    prod.close()
    cons.close()


def test_shm_endpoint_carries_frames_byte_identically(tmp_path):
    """Two endpoints over a crossed ring pair move encode_tensors frames
    unchanged — the structural bit-identity claim at the object level."""
    a2b = net.ShmFrameRing.create(str(tmp_path / "a2b"), "producer")
    b2a_path = str(tmp_path / "b2a")
    b2a = net.ShmFrameRing.create(b2a_path, "consumer")
    sa, sb = socket.socketpair()
    end_a = net.ShmEndpoint(sa, a2b, b2a)
    end_b = net.ShmEndpoint(sb, net.ShmFrameRing.open(b2a_path, "producer"),
                            net.ShmFrameRing.open(str(tmp_path / "a2b"),
                                                  "consumer"))
    end_a.settimeout(2.0)
    end_b.settimeout(2.0)
    arrays = [np.arange(12, dtype=np.float32),
              np.ones((3, 4), np.float32)]
    frame = net.encode_tensors(net.ACTION_COMMIT, arrays)
    net.send_frame(end_a, frame)
    payload = net.recv_frame(end_b)
    assert bytes(payload) == bytes(frame)
    action, blobs = net.decode_tensors(payload)
    assert action == net.ACTION_COMMIT
    np.testing.assert_array_equal(
        np.frombuffer(blobs[0], np.float32), arrays[0])
    end_a.close()
    end_b.close()


# -- the handshake codec -------------------------------------------------------

def test_shm_handshake_codec_roundtrips():
    action, blobs = net.decode_tensors(net.encode_shm_request(1 << 16))
    assert action == net.ACTION_SHM
    assert net.decode_shm_request(blobs) == (net.SHM_VERSION, 1 << 16)

    action, blobs = net.decode_tensors(net.encode_shm_offer("/a.c2h",
                                                            "/b.h2c"))
    assert action == net.ACTION_SHM
    assert net.decode_shm_offer(blobs) == ("/a.c2h", "/b.h2c")

    _, blobs = net.decode_tensors(net.encode_shm_decline())
    assert net.decode_shm_offer(blobs) is None  # decline = zero blobs

    for attached in (True, False):
        _, blobs = net.decode_tensors(net.encode_shm_confirm(attached))
        assert net.decode_shm_confirm(blobs) is attached

    with pytest.raises(net.ProtocolError):
        net.decode_shm_request([b"\x01"])  # truncated header blob
    with pytest.raises(net.ProtocolError):
        net.decode_shm_offer([b"/only-one-path"])


# -- live negotiation against a real hub ---------------------------------------

def _weights():
    return [np.zeros((4, 4), np.float32), np.zeros((6,), np.float32)]


def test_psclient_attaches_and_center_matches_tcp(tmp_path):
    """A shm=True client negotiates onto the rings (transport == "shm",
    ring files unlinked after the handshake), and the hub center after a
    session is identical to the same session over plain TCP."""
    t = _weights()
    results = {}
    for shm in (False, True):
        hub = DeltaParameterServer([w.copy() for w in t], port=0,
                                   idle_timeout=None,
                                   shm_dir=str(tmp_path))
        hub.start()
        try:
            with PSClient("127.0.0.1", hub.port, templates=t,
                          shm=shm) as c:
                assert c.transport == ("shm" if shm else "tcp")
                c.pull()
                c.commit([np.full_like(w, 0.25) for w in t])
                pulled = [w.copy() for w in c.pull()]
            results[shm] = ([w.copy() for w in hub.center], pulled)
        finally:
            hub.stop()
    (center_tcp, pulled_tcp), (center_shm, pulled_shm) = \
        results[False], results[True]
    for x, y in zip(center_tcp, center_shm):
        np.testing.assert_array_equal(x, y)
    for x, y in zip(pulled_tcp, pulled_shm):
        np.testing.assert_array_equal(x, y)
    # handshake cleanup: no ring files left behind
    assert [f for f in os.listdir(str(tmp_path)) if f.startswith("ring-")] \
        == []


def test_psclient_decline_falls_back_to_tcp():
    """A hub without shm_dir declines the Z request; the client degrades
    to plain TCP and the session still works."""
    t = _weights()
    hub = DeltaParameterServer([w.copy() for w in t], port=0,
                               idle_timeout=None)
    hub.start()
    try:
        with PSClient("127.0.0.1", hub.port, templates=t, shm=True) as c:
            assert c.transport == "tcp"
            c.pull()
            c.commit([np.full_like(w, 0.5) for w in t])
            c.drain()
        assert float(hub.center[0][0, 0]) == 0.5
    finally:
        hub.stop()


def test_shm_counters_flow_during_attached_session(tmp_path):
    t = _weights()
    hub = DeltaParameterServer([w.copy() for w in t], port=0,
                               idle_timeout=None, shm_dir=str(tmp_path))
    hub.start()
    obs.reset()
    obs.enable()
    try:
        with PSClient("127.0.0.1", hub.port, templates=t, shm=True) as c:
            assert c.transport == "shm"
            for _ in range(4):
                c.pull()
                c.commit([np.full_like(w, 0.1) for w in t])
        counters = obs.snapshot()["counters"]
        assert counters.get("ps.shm_frames_total", 0) > 0
    finally:
        obs.disable()
        obs.reset()
        hub.stop()


# -- the batched receiver ------------------------------------------------------

def _frames(n):
    t = [np.full((3,), float(i), np.float32) for i in range(2)]
    payload = bytes(net.encode_tensors(net.ACTION_COMMIT, t))
    return [len(payload).to_bytes(8, "big") + payload for _ in range(n)]


def test_batched_receiver_parses_a_burst_and_tracks_pending():
    """A burst of queued frames is served from buffered bytes (pending
    drains to 0 only after the last frame), each parsed view matching
    what recv_frame would have produced."""
    a, b = socket.socketpair()
    try:
        frames = _frames(6)
        a.sendall(b"".join(frames))
        rx = net.BatchedReceiver(b, frame_hint=len(frames[0]), depth=4)
        for want in frames:
            view = rx.recv_frame_into()
            assert bytes(view) == want[8:]  # payload, header stripped
        assert rx.pending() == 0
    finally:
        a.close()
        b.close()


def test_batched_receiver_observes_batch_depth_histogram():
    a, b = socket.socketpair()
    obs.reset()
    obs.enable()
    try:
        frames = _frames(5)
        rx = net.BatchedReceiver(b, frame_hint=len(frames[0]), depth=4)
        a.sendall(b"".join(frames))
        for _ in frames:
            rx.recv_frame_into()
        # the histogram records on the NEXT blocking fill; trigger it
        a.sendall(frames[0])
        rx.recv_frame_into()
        hist = obs.snapshot()["histograms"].get("ps_recv_batch_depth") or {}
        assert (hist.get("count") or 0) >= 1
        assert (hist.get("max") or 0) >= 2  # the burst actually batched
    finally:
        obs.disable()
        obs.reset()
        a.close()
        b.close()


def test_batched_io_guard_is_bool_and_types_cached():
    avail = net.batched_io_available()
    assert isinstance(avail, bool)
    if avail:  # resolvable symbol implies the ctypes scaffolding works
        ctypes_mod, iovec, mmsghdr = net._mmsg_types()
        assert ctypes_mod.sizeof(iovec) in (8, 16)


# -- wire pins -----------------------------------------------------------------

class _RecordingSock:
    def __init__(self, sock):
        self._sock = sock
        self.tx = bytearray()

    def sendall(self, data):
        self.tx += bytes(data)
        return self._sock.sendall(data)

    def __getattr__(self, name):
        return getattr(self._sock, name)


def _session_bytes(port, templates):
    with PSClient("127.0.0.1", port, templates=templates) as c:
        rec = _RecordingSock(c.sock)
        c.sock = rec
        c.pull()
        c.commit([np.full_like(t, 0.5) for t in templates])
        c.pull()
        c.drain()
    return bytes(rec.tx)


def test_quickack_and_recv_batch_leave_client_bytes_identical(tmp_path):
    """The hub-side perf knobs (TCP_QUICKACK on accept, recvmmsg batch
    depth, an attached shm_dir) are invisible on the wire: an un-upgraded
    client's byte stream is identical against a plain hub and a
    fully-tuned one, and carries no Z frame."""
    t = _weights()
    plain = DeltaParameterServer([w.copy() for w in t], port=0,
                                 idle_timeout=None)
    plain.start()
    tuned = DeltaParameterServer([w.copy() for w in t], port=0,
                                 idle_timeout=None,
                                 shm_dir=str(tmp_path), recv_batch_depth=8)
    tuned.start()
    try:
        baseline = _session_bytes(plain.port, t)
        against_tuned = _session_bytes(tuned.port, t)
    finally:
        plain.stop()
        tuned.stop()
    assert baseline == against_tuned
    i = 0
    while i < len(baseline):  # stream stays attach-free
        n = int.from_bytes(baseline[i:i + 8], "big")
        assert baseline[i + 8:i + 9] != net.ACTION_SHM
        i += 8 + n
