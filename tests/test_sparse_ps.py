"""Row-sparse embedding parameter service (issue 9): sparse wire framing
(actions S/V/U/X), hub row apply under the staleness clock, row-range
sharding, client caches + int8 dense-residual fallback, trainer threading,
wire-compat matrix (recording sockets), and sparse-vs-dense bit-parity."""

import time

import numpy as np
import pytest

from distkeras_tpu import observability as obs
from distkeras_tpu.runtime import networking as net
from distkeras_tpu.runtime.parameter_server import (
    ADAGParameterServer,
    DeltaParameterServer,
    InprocPSClient,
    PSClient,
    ShardedParameterServer,
    ShardedPSClient,
    shard_plan,
)


def _weights():
    return [np.arange(32, dtype=np.float32).reshape(8, 4),
            np.zeros((3,), np.float32)]


def _start(hub_cls=DeltaParameterServer, sparse=(0,), **kw):
    ps = hub_cls(_weights(), idle_timeout=None, sparse_leaves=sparse, **kw)
    ps.start()
    return ps


# -- wire framing --------------------------------------------------------------

def test_var_frame_encoder_bytes_identical_to_generic():
    enc = net.VarFrameEncoder(initial=8)  # force at least one grow
    for arrays in ([np.arange(5, dtype=np.int64)],
                   [np.zeros(0, np.int64), np.ones((3, 4), np.float32)],
                   [np.frombuffer(b"xy", np.uint8)]):
        frame = bytes(enc.pack(net.ACTION_SPARSE_COMMIT, arrays))
        generic = net.encode_tensors(net.ACTION_SPARSE_COMMIT, arrays)
        assert frame[8:] == generic
        assert frame[:8] == len(generic).to_bytes(8, "big")
        assert enc.frame_len == len(frame)
        action, blobs = net.decode_tensor_views(memoryview(frame)[8:])
        assert action == net.ACTION_SPARSE_COMMIT
        assert len(blobs) == len(arrays)


def test_normalize_row_ids():
    out = net.normalize_row_ids([3, 1, 3, 0], rows=8)
    np.testing.assert_array_equal(out, [0, 1, 3])
    assert out.dtype == np.int64
    assert net.normalize_row_ids([], rows=8).size == 0
    with pytest.raises(ValueError):
        net.normalize_row_ids([8], rows=8)
    with pytest.raises(ValueError):
        net.normalize_row_ids([-1], rows=8)


# -- hub validation ------------------------------------------------------------

def test_hub_rejects_bad_sparse_config():
    with pytest.raises(ValueError):
        DeltaParameterServer(_weights(), sparse_leaves=[5])
    with pytest.raises(ValueError):
        DeltaParameterServer(_weights(), sparse_leaves=[1])  # not 2-D


def test_sparse_actions_against_dense_hub_drop_connection():
    ps = DeltaParameterServer(_weights(), idle_timeout=None)
    ps.start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      sparse_leaves=[0]) as c:
            with pytest.raises((ConnectionError, ValueError, OSError)):
                c.pull_nowait(sparse_rows=[np.array([0, 1])])
                c.wait_weights()
    finally:
        ps.stop()


def test_malformed_row_ids_drop_connection_hub_survives():
    """Unsorted / duplicate / out-of-range ids desync that connection
    (ProtocolError path) but the hub keeps serving other clients."""
    ps = _start()
    try:
        raw = net.connect("127.0.0.1", ps.port)
        try:
            net.send_tensors(raw, net.ACTION_SPARSE_PULL,
                             [np.array([3, 1], np.int64)])  # unsorted
            with pytest.raises((ConnectionError, OSError)):
                got = net.recv_frame(raw, limit=1 << 20)
                if not got:
                    raise ConnectionError("closed")
        finally:
            raw.close()
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      sparse_leaves=[0]) as c:
            c.pull_nowait(sparse_rows=[np.array([0])])
            assert c.wait_weights()[1].shape == (3,)
    finally:
        ps.stop()


# -- hub apply under the staleness clock ---------------------------------------

def test_sparse_commit_applies_commit_scale():
    """An ADAG hub scales sparse row grads exactly like dense commits
    (delta / num_workers), touching ONLY the committed rows, and the
    clock/staleness bookkeeping advances once per sparse commit."""
    ps = _start(ADAGParameterServer, num_workers=4)
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      sparse_leaves=[0]) as c:
            c.pull()
            d = [np.zeros((8, 4), np.float32), np.ones((3,), np.float32)]
            d[0][2] = 8.0
            c.commit(d, sparse_rows=[np.array([2, 5])])
        got = ps.get_weights()
        base = _weights()
        np.testing.assert_allclose(got[0][2], base[0][2] + 2.0)  # 8/4
        np.testing.assert_allclose(got[0][5], base[0][5])  # zero grad row
        np.testing.assert_allclose(got[0][0], base[0][0])  # untouched
        np.testing.assert_allclose(got[1], 0.25)
        assert ps.num_updates == 1 and ps._clock == 1
    finally:
        ps.stop()


def test_sparse_commit_respects_clock_fence():
    """A sparse commit carrying a pre-restore pull clock is fenced exactly
    like a dense one (staleness re-based at the restore point)."""
    ps = _start(hub_cls=DeltaParameterServer)
    try:
        ids = [np.array([0])]
        values, clock = ps.pull_sparse_direct(ids)
        ps.restore_state([w + 1 for w in _weights()], {"clock": 50})
        grads = np.ones((1, 4), np.float32)
        ps.commit_sparse_direct(
            [(ids[0], grads), np.zeros(3, np.float32)], clock)
        # fence clamps: staleness 0, applied once
        assert ps._clock == 51
        np.testing.assert_allclose(ps.get_weights()[0][0],
                                   _weights()[0][0] + 2.0)
    finally:
        ps.stop()


def test_sparse_replication_feeds_row_deltas():
    """A replicated primary materializes the applied row delta into the
    existing center-shaped R feed: the standby's center tracks sparse
    commits bit for bit."""
    primary = _start()
    replica = DeltaParameterServer(
        _weights(), idle_timeout=None, sparse_leaves=[0],
        replica_of=("127.0.0.1", primary.port))
    replica.start()
    try:
        assert replica.wait_synced(timeout=10)
        with PSClient("127.0.0.1", primary.port, templates=_weights(),
                      sparse_leaves=[0]) as c:
            c.pull()
            d = [np.zeros((8, 4), np.float32), np.ones((3,), np.float32)]
            d[0][1] = 3.0
            c.commit(d, sparse_rows=[np.array([1, 6])])
        import time
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and replica._clock < 1:
            time.sleep(0.01)
        for a, b in zip(primary.get_weights(), replica.get_weights()):
            np.testing.assert_array_equal(a, b)
    finally:
        replica.stop()
        primary.stop()


# -- row-range shard plan ------------------------------------------------------

def test_shard_plan_sparse_row_ranges_partition_rows():
    t = [np.zeros((10, 4), np.float32), np.zeros((64,), np.float32),
         np.zeros((3, 3), np.float32)]
    plan = shard_plan(t, 3, sparse_leaves=[0])
    ranges = plan.sparse_ranges[0]
    assert len(ranges) == 3
    assert ranges[0][0] == 0 and ranges[-1][1] == 10
    for (a, b), (c, _) in zip(ranges, ranges[1:]):
        assert b == c and b > a
    # every shard lists the sparse leaf; dense leaves appear exactly once
    for sid in range(3):
        assert 0 in plan.assignments[sid]
        assert plan.local_sparse(sid) == (plan.assignments[sid].index(0),)
    dense_counts = [sum(1 for idxs in plan.assignments for i in idxs
                        if i == leaf) for leaf in (1, 2)]
    assert dense_counts == [1, 1]
    assert plan.num_leaves == 3


def test_shard_plan_sparse_split_assemble_roundtrip():
    t = [np.arange(40, dtype=np.float32).reshape(10, 4),
         np.arange(5, dtype=np.float32)]
    plan = shard_plan(t, 2, sparse_leaves=[0])
    parts = plan.split(t)
    # split returns row-range views, zero copy
    assert parts[0][0].base is t[0] or parts[0][0].base is t[0].base
    back = plan.assemble(parts)
    np.testing.assert_array_equal(back[0], t[0])
    np.testing.assert_array_equal(back[1], t[1])
    # sparse_fill substitutes the full array without concatenating
    full = np.zeros((10, 4), np.float32)
    filled = plan.assemble(parts, sparse_fill={0: full})
    assert filled[0] is full


def test_shard_plan_sparse_validation():
    t = [np.zeros((3, 4), np.float32), np.zeros((5,), np.float32)]
    with pytest.raises(ValueError):
        shard_plan(t, 4, sparse_leaves=[0])  # 3 rows < 4 shards
    with pytest.raises(ValueError):
        shard_plan(t, 2, sparse_leaves=[1])  # not 2-D
    # dense behavior unchanged: a sparse-free plan is the PR-6 plan
    plan = shard_plan(t, 2)
    assert plan.sparse_ranges == {}
    assert plan.num_leaves == 2


def test_shard_plan_dense_unchanged_by_sparse_arg_default():
    t = [np.zeros((4, 4), np.float32), np.zeros((6,), np.float32),
         np.zeros((3,), np.float32)]
    a = shard_plan(t, 2)
    b = shard_plan(t, 2, sparse_leaves=())
    assert a.assignments == b.assignments
    assert a.shard_bytes == b.shard_bytes


# -- wire compatibility (recording-socket matrix) ------------------------------

class _RecordingSock:
    def __init__(self, sock):
        self._sock = sock
        self.tx = bytearray()

    def sendall(self, data):
        self.tx += bytes(data)
        return self._sock.sendall(data)

    def __getattr__(self, name):
        return getattr(self._sock, name)


_SPARSE_ACTIONS = (net.ACTION_SPARSE_PULL, net.ACTION_SPARSE_WEIGHTS,
                   net.ACTION_SPARSE_COMMIT, net.ACTION_SPARSE_QCOMMIT)


def _assert_no_sparse_frames(stream: bytes) -> None:
    i = 0
    while i < len(stream):
        n = int.from_bytes(stream[i:i + 8], "big")
        assert stream[i + 8:i + 9] not in _SPARSE_ACTIONS
        i += 8 + n


def _plain_session_bytes(port, templates):
    with PSClient("127.0.0.1", port, templates=templates) as c:
        rec = _RecordingSock(c.sock)
        c.sock = rec
        c.pull()
        c.commit([np.full_like(t, 0.5) for t in templates])
        c.pull()
        c.drain()
    return bytes(rec.tx)


def test_plain_client_bytes_identical_against_sparse_capable_hub():
    """The zero-sparse-tables pin: an un-upgraded client's byte stream is
    identical whether the hub has sparse tables registered or not, and
    never contains an S/V/U/X frame."""
    t = _weights()
    plain = DeltaParameterServer(t, port=0, idle_timeout=None)
    plain.start()
    sparse = DeltaParameterServer(t, port=0, idle_timeout=None,
                                  sparse_leaves=[0])
    sparse.start()
    try:
        baseline = _plain_session_bytes(plain.port, t)
        against_sparse = _plain_session_bytes(sparse.port, t)
    finally:
        plain.stop()
        sparse.stop()
    assert baseline == against_sparse
    _assert_no_sparse_frames(baseline)


def test_plain_striped_client_bytes_identical_on_sparse_capable_shards():
    """The sharded cell: per-stripe byte streams of a dense striped
    session are identical whether or not the shard hubs have their sparse
    row ranges registered (same row-range plan both sides)."""
    t = [np.arange(40, dtype=np.float32).reshape(10, 4),
         np.zeros((6,), np.float32), np.zeros((3,), np.float32)]
    plan = shard_plan(t, 2, sparse_leaves=[0])

    def make(with_sparse):
        ps = ShardedParameterServer(
            t, plan, lambda w, sid: DeltaParameterServer(
                w, shard_id=sid, idle_timeout=None,
                sparse_leaves=(plan.local_sparse(sid)
                               if with_sparse else ())))
        ps.start()
        return ps

    def session(ps):
        with ShardedPSClient([("127.0.0.1", p) for p in ps.ports],
                             t, plan) as c:
            recs = []
            for sc in c.shards:
                rec = _RecordingSock(sc.sock)
                sc.sock = rec
                recs.append(rec)
            c.pull()
            c.commit([np.full_like(a, 0.5) for a in t])
            c.pull()
            c.drain()
        return [bytes(r.tx) for r in recs]

    on, off = make(True), make(False)
    try:
        streams_on = session(on)
        streams_off = session(off)
    finally:
        on.stop()
        off.stop()
    assert streams_on == streams_off
    for s in streams_on:
        _assert_no_sparse_frames(s)


def test_plain_client_bytes_identical_on_replicated_sparse_hub():
    """The replicated cell: a sparse-capable primary streaming to a hot
    standby serves an un-upgraded client the same byte conversation as a
    plain unreplicated hub."""
    t = _weights()
    plain = DeltaParameterServer(t, port=0, idle_timeout=None)
    plain.start()
    primary = DeltaParameterServer(t, port=0, idle_timeout=None,
                                   sparse_leaves=[0])
    primary.start()
    replica = DeltaParameterServer(t, port=0, idle_timeout=None,
                                   sparse_leaves=[0],
                                   replica_of=("127.0.0.1", primary.port))
    replica.start()
    try:
        assert replica.wait_synced(timeout=10)
        baseline = _plain_session_bytes(plain.port, t)
        against = _plain_session_bytes(primary.port, t)
    finally:
        replica.stop()
        primary.stop()
        plain.stop()
    assert baseline == against
    _assert_no_sparse_frames(against)


# -- client behavior -----------------------------------------------------------

def test_sparse_pull_merges_into_cache_and_full_pull_reseeds():
    ps = _start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      sparse_leaves=[0]) as writer:
            writer.pull()
            d = [np.zeros((8, 4), np.float32), np.zeros((3,), np.float32)]
            d[0][4] = 1.0
            writer.commit(d, sparse_rows=[np.array([4])])
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      sparse_leaves=[0]) as c:
            c.pull()  # full pull seeds cache with the hub's center
            c.pull_nowait(sparse_rows=[np.array([0])])
            w = c.wait_weights()
            # row 4 came from the FULL pull; row 0 from the sparse merge
            np.testing.assert_allclose(w[0][4], _weights()[0][4] + 1.0)
            assert w[0] is c._cache[0]
    finally:
        ps.stop()


def test_sparse_pull_reissued_after_reconnect():
    """A severed reply mid-sparse-pull reconnects and re-asks for the SAME
    rows (the _sparse_pull_ids FIFO survives the reconnect)."""
    from distkeras_tpu.runtime.faults import ChaosProxy, Fault, FaultPlan

    ps = _start()
    plan = FaultPlan([Fault(conn=0, direction="s2c", frame=1,
                            kind="sever")])
    try:
        with ChaosProxy("127.0.0.1", ps.port, plan) as proxy:
            with PSClient("127.0.0.1", proxy.port, templates=_weights(),
                          sparse_leaves=[0], max_reconnects=5,
                          reconnect_backoff=0.02) as c:
                c.pull()  # frame 0 reply: full weights (survives)
                c.pull_nowait(sparse_rows=[np.array([1, 2])])
                w = c.wait_weights()  # frame 1 reply severed -> re-pulled
                np.testing.assert_allclose(w[0][1], _weights()[0][1])
                assert c.reconnects_used == 1
                assert not c._sparse_pull_ids
    finally:
        ps.stop()


def test_int8_sparse_commit_error_feedback_converges():
    """Dense-residual fallback: repeated int8 sparse commits of the same
    delta track the true sum (error feedback over touched rows)."""
    ps = _start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      sparse_leaves=[0], compress="int8") as c:
            c.pull()
            d = [np.zeros((8, 4), np.float32), np.zeros((3,), np.float32)]
            d[0][3] = np.array([0.3, -0.7, 1.1, 0.01], np.float32)
            for _ in range(50):
                c.commit(d, sparse_rows=[np.array([3])])
        got = ps.get_weights()[0][3] - _weights()[0][3]
        np.testing.assert_allclose(got, 50 * d[0][3], rtol=0.02, atol=0.02)
    finally:
        ps.stop()


def test_inproc_sparse_matches_socket_trajectory():
    """Transport parity, extended to sparse: a deterministic single-worker
    schedule of partial-touch pulls/commits lands the identical center on
    both transports (incl. int8)."""
    for compress in (None, "int8"):
        results = []
        for transport in ("socket", "inproc"):
            ps = _start()
            try:
                if transport == "socket":
                    client = PSClient("127.0.0.1", ps.port,
                                      templates=_weights(),
                                      sparse_leaves=[0], compress=compress)
                else:
                    client = InprocPSClient(ps, templates=_weights(),
                                            sparse_leaves=[0],
                                            compress=compress)
                with client as c:
                    c.pull()
                    rng = np.random.default_rng(0)
                    for step in range(5):
                        ids = np.unique(rng.integers(0, 8, size=4))
                        c.pull_nowait(sparse_rows=[ids])
                        w = c.wait_weights()
                        d = [np.zeros((8, 4), np.float32),
                             np.full((3,), 0.1, np.float32)]
                        d[0][ids] = rng.normal(size=(ids.size, 4)) \
                            .astype(np.float32)
                        c.commit(d, sparse_rows=[ids])
                results.append([w.copy() for w in ps.get_weights()])
            finally:
                ps.stop()
        for a, b in zip(*results):
            np.testing.assert_array_equal(a, b)


def test_pipelined_sparse_commit_drains_pending_sparse_pull_first():
    """Review pin: the deadlock-avoidance drain before a large commit send
    claims pending SPARSE weights replies too (the dense rule — never
    start a big send while a reply may be in flight — applies to V
    frames, which carry the dense leaves whole)."""
    ps = _start()
    try:
        with PSClient("127.0.0.1", ps.port, templates=_weights(),
                      sparse_leaves=[0]) as c:
            c.pull()
            ids = np.array([0, 1])
            c.pull_nowait(sparse_rows=[ids])
            d = [np.zeros((8, 4), np.float32), np.ones((3,), np.float32)]
            c.commit_nowait(d, sparse_rows=[ids])
            # the sparse reply was consumed into _ready BEFORE the commit
            # bytes left; only the commit ack remains pending
            assert not c._has_pending(net.ACTION_SPARSE_WEIGHTS)
            assert len(c._ready) == 1
            w = c.wait_weights()
            assert w[0] is c._cache[0]
            c.drain()
    finally:
        ps.stop()


def test_pull_sparse_direct_rejects_wrong_id_array_count():
    """Review pin: too many id arrays is an error, not a silent
    truncation (the zip would otherwise drop the extras)."""
    ps = _start()
    try:
        with pytest.raises(ValueError, match="id arrays"):
            ps.pull_sparse_direct([np.array([0]), np.array([1])])
    finally:
        ps.stop()


def test_mismatched_sparse_table_row_counts_refused_at_setup():
    """Review pin: explicitly-named sparse tables with unequal row counts
    are refused at train() setup (the worker sends ONE shared id set per
    window; a mid-run out-of-range id would kill the run instead)."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG

    mlp = ModelSpec(name="mlp", config={"hidden_sizes": (6,),
                                        "num_outputs": 2},
                    input_shape=(4,))
    model = Model.init(mlp, seed=0)
    import jax

    kernels = tuple(i for i, leaf in enumerate(jax.tree.leaves(model.params))
                    if np.asarray(leaf).ndim == 2)
    assert len(kernels) == 2  # (4,6) and (6,2) kernels: unequal rows
    tr = AsyncADAG(model, sparse_tables=kernels,
                   loss="categorical_crossentropy")
    rng = np.random.default_rng(0)
    ds = Dataset({
        "features": rng.normal(size=(16, 4)).astype(np.float32),
        "label": np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)],
    })
    with pytest.raises(ValueError, match="mismatched row counts"):
        tr.train(ds, shuffle=False)


# -- trainer e2e ---------------------------------------------------------------

def _full_touch_dataset(rows, fields, batch, window, n_windows):
    """Every window's batches cover ALL row ids — the full-touch shape the
    bit-parity pin needs."""
    from distkeras_tpu.data.dataset import Dataset

    n = batch * window * n_windows
    total = n * fields
    reps = -(-total // rows)
    ids = np.tile(np.arange(rows, dtype=np.int32), reps)[:total]
    labels = np.eye(2, dtype=np.float32)[
        np.arange(n) % 2]
    return Dataset({"features": ids.reshape(n, fields), "label": labels})


def _ctr_trainer(spec, sparse, **kw):
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.runtime.async_trainer import AsyncADAG

    defaults = dict(loss="categorical_crossentropy", batch_size=4,
                    num_epoch=2, learning_rate=0.05, seed=0, num_workers=1,
                    communication_window=2,
                    sparse_tables="auto" if sparse else None)
    defaults.update(kw)
    return AsyncADAG(Model.init(spec, seed=0), **defaults)


def _native_mark():
    from distkeras_tpu.runtime.native import build_error, native_available

    return pytest.mark.skipif(not native_available(),
                              reason=f"native PS unavailable: {build_error()}")


# hub dimension (ISSUE 11): the C++ hub serves the sparse wire plane, so
# THE acceptance pin runs against both implementations.  Tier-1 keeps the
# cheapest native cell (PR-6 convention); the rest of the native matrix
# rides the slow suite
@pytest.mark.parametrize("compress,pipeline,epochs,hub", [
    (None, True, 1, "python"),
    pytest.param(None, False, 2, "python", marks=pytest.mark.slow),
    ("int8", True, 1, "python"),
    pytest.param("int8", False, 2, "python", marks=pytest.mark.slow),
    pytest.param(None, True, 1, "native", marks=_native_mark()),
    pytest.param("int8", True, 1, "native",
                 marks=[_native_mark(), pytest.mark.slow]),
    pytest.param(None, False, 2, "native",
                 marks=[_native_mark(), pytest.mark.slow]),
    pytest.param("int8", False, 2, "native",
                 marks=[_native_mark(), pytest.mark.slow]),
])
def test_sparse_vs_dense_full_touch_bit_parity(compress, pipeline, epochs,
                                               hub):
    """THE acceptance pin: a 1-worker run whose every window touches every
    row lands bit-identical final weights sparse vs dense (full-touch row
    gathers carry exactly the dense payload; the hub applies the same
    scaled adds; for int8 the full-row block quantizes with the same
    per-leaf scale the dense path uses).

    Pipelined parity is pinned within one epoch: across an epoch boundary
    the sparse exchange deliberately skips the cross-epoch prefetch (the
    next epoch's reshuffled row ids don't exist yet), so its boundary
    pull observes one commit more than the dense prefetch does — the
    serial exchange (pipeline=False) has no prefetch and stays
    bit-identical across any number of epochs."""
    import jax

    from distkeras_tpu.models.embedding import ctr_embedding_spec

    spec = ctr_embedding_spec(8, dim=4, fields=2, hidden_sizes=(4,))
    ds = _full_touch_dataset(8, 2, batch=4, window=2, n_windows=2)
    finals = []
    for sparse in (True, False):
        tr = _ctr_trainer(spec, sparse, compress_commits=compress,
                          pipeline=pipeline, num_epoch=epochs,
                          native_ps=(hub == "native"))
        model = tr.train(ds, shuffle=False)
        finals.append(jax.tree.leaves(model.params))
    for a, b in zip(*finals):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sparse_sharded_matches_unsharded_bit_parity():
    """Row-range striping parity: 1-shard and 3-shard sparse runs land the
    identical final center (disjoint row ranges -> per-commit adds apply
    to the same elements in the same order)."""
    import jax

    from distkeras_tpu.models.embedding import ctr_embedding_spec

    spec = ctr_embedding_spec(9, dim=4, fields=2, hidden_sizes=(4,))
    ds = _full_touch_dataset(9, 2, batch=4, window=2, n_windows=2)
    finals = []
    for shards in (1, 3):
        tr = _ctr_trainer(spec, sparse=True, num_shards=shards)
        model = tr.train(ds, shuffle=False)
        finals.append(jax.tree.leaves(model.params))
    for a, b in zip(*finals):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sparse_inproc_trainer_matches_socket():
    import jax

    from distkeras_tpu.models.embedding import ctr_embedding_spec

    spec = ctr_embedding_spec(8, dim=4, fields=2, hidden_sizes=(4,))
    ds = _full_touch_dataset(8, 2, batch=4, window=2, n_windows=2)
    finals = []
    for transport in ("socket", "inproc"):
        tr = _ctr_trainer(spec, sparse=True, transport=transport)
        model = tr.train(ds, shuffle=False)
        finals.append(jax.tree.leaves(model.params))
    for a, b in zip(*finals):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sparse_trainer_partial_touch_trains_and_counts_rows():
    """A skewed CTR run (partial touch) trains to a finite loss while the
    hub's sparse telemetry counts rows and wire bytes saved."""
    from distkeras_tpu.data.ctr import synthetic_ctr_dataset
    from distkeras_tpu.models.embedding import ctr_embedding_spec

    spec = ctr_embedding_spec(64, dim=4, fields=2, hidden_sizes=(4,))
    ds = synthetic_ctr_dataset(64, 64, fields=2, seed=0)
    obs.enable()
    obs.reset()
    try:
        tr = _ctr_trainer(spec, sparse=True, num_workers=2, batch_size=4)
        tr.train(ds, shuffle=False)
        assert tr.history and np.isfinite(tr.history[-1])
        snap = obs.snapshot()
        assert snap["counters"].get("ps.sparse_rows_pulled", 0) > 0
        assert snap["counters"].get("ps.sparse_rows_committed", 0) > 0
        assert snap["counters"].get("ps.sparse_wire_bytes_saved", 0) > 0
        # fleet_report surfaces the row traffic from the commit/pull spans
        from distkeras_tpu.observability.distributed import fleet_report

        report = fleet_report(events=obs.TRACER.events())
        assert report["sparse"]["rows_committed"] > 0
        assert report["sparse"]["rows_pulled"] > 0
    finally:
        obs.reset()
        obs.disable()


def test_sparse_sharded_telemetry_is_shard_labeled():
    t = [np.zeros((10, 4), np.float32), np.zeros((3,), np.float32)]
    plan = shard_plan(t, 2, sparse_leaves=[0])
    obs.enable()
    obs.reset()
    ps = ShardedParameterServer(
        t, plan, lambda w, sid: DeltaParameterServer(
            w, shard_id=sid, idle_timeout=None,
            sparse_leaves=plan.local_sparse(sid)))
    ps.start()
    try:
        addrs = [("127.0.0.1", p) for p in ps.ports]
        with ShardedPSClient(addrs, t, plan, sparse_leaves=[0]) as c:
            c.pull()
            d = [np.ones((10, 4), np.float32), np.ones((3,), np.float32)]
            c.commit(d, sparse_rows=[np.array([1, 8])])  # one id per range
        # the hub acks a commit BEFORE its telemetry tail runs (ack
        # latency beats counter bumps by design), so an immediate
        # snapshot races the handler thread — poll briefly (the exact
        # unguarded-read-after-ack shape ISSUE 14 is about)
        keys = [f'ps.sparse_rows_committed{{shard="{sid}"}}'
                for sid in ("0", "1")]
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            snap = obs.snapshot()
            if all(snap["counters"].get(k) == 1.0 for k in keys):
                break
            time.sleep(0.01)
        for key in keys:
            assert snap["counters"].get(key) == 1.0, snap["counters"]
    finally:
        ps.stop()
        obs.reset()
        obs.disable()


def test_sparse_health_reports_carry_row_rate():
    """Workers with health reporting on stream sparse_rows_total; the
    collector series and distkeras-top's ROW/S column see it."""
    from distkeras_tpu.data.ctr import synthetic_ctr_dataset
    from distkeras_tpu.models.embedding import ctr_embedding_spec
    from distkeras_tpu.observability import health as health_mod

    spec = ctr_embedding_spec(32, dim=4, fields=2, hidden_sizes=(4,))
    ds = synthetic_ctr_dataset(64, 32, fields=2, seed=0)
    health_mod.reset_default()
    try:
        tr = _ctr_trainer(spec, sparse=True, health_interval_s=0.05,
                          batch_size=4)
        tr.train(ds, shuffle=False)
        snap = health_mod.collector().snapshot()
        worker = snap["workers"]["0"]
        series = worker["metrics"].get("sparse_rows_total")
        assert series is not None and series["last"] > 0
        frame = health_mod.render_top(
            {"fleet": snap, "events": []})
        assert "ROW/S" in frame
    finally:
        health_mod.reset_default()


def test_sparse_knob_validation():
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.models.embedding import ctr_embedding_spec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG

    spec = ctr_embedding_spec(8, dim=4, fields=2)
    # every transport x hub cell composes with sparse_tables since
    # ISSUE 15 (the C++ hub serves the sparse direct pair too): both
    # native combinations construct cleanly now
    AsyncADAG(Model.init(spec, seed=0), sparse_tables="auto",
              native_ps=True, loss="categorical_crossentropy")
    AsyncADAG(Model.init(spec, seed=0), sparse_tables="auto",
              native_ps=True, transport="inproc",
              loss="categorical_crossentropy")
    with pytest.raises(ValueError, match="inproc"):
        tr = AsyncADAG(Model.init(spec, seed=0), sparse_tables="auto",
                       transport="inproc", num_shards=2,
                       loss="categorical_crossentropy")
        tr.train(_full_touch_dataset(8, 2, 4, 2, 2), shuffle=False)
    mlp = ModelSpec(name="mlp", config={"hidden_sizes": (4,),
                                        "num_outputs": 2},
                    input_shape=(4,))
    with pytest.raises(ValueError, match="declares no sparse"):
        tr = AsyncADAG(Model.init(mlp, seed=0), sparse_tables="auto",
                       loss="categorical_crossentropy")
        from distkeras_tpu.data.dataset import Dataset

        rng = np.random.default_rng(0)
        tr.train(Dataset({
            "features": rng.normal(size=(16, 4)).astype(np.float32),
            "label": np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)],
        }), shuffle=False)


def test_sparse_leaf_indices_resolution():
    from distkeras_tpu.models.base import Model, sparse_leaf_indices
    from distkeras_tpu.models.embedding import ctr_embedding_spec

    spec = ctr_embedding_spec(8, dim=4, fields=2)
    model = Model.init(spec, seed=0)
    idx = sparse_leaf_indices(spec, model.params)
    assert len(idx) == 1
    import jax

    leaf = jax.tree.leaves(model.params)[idx[0]]
    assert leaf.shape == (8, 4)


def test_launcher_standalone_sparse_hub_worker_only_mode():
    """distkeras-ps-style standalone sparse hub + worker-only trainer:
    both ends derive the same sparse leaf set from the same model."""
    import jax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.embedding import ctr_embedding_spec
    from distkeras_tpu.runtime.launcher import start_parameter_server

    spec = ctr_embedding_spec(8, dim=4, fields=2, hidden_sizes=(4,))
    model = Model.init(spec, seed=0)
    ps = start_parameter_server(model, mode="adag", num_workers=1,
                                host="127.0.0.1", idle_timeout=None,
                                sparse_tables="auto")
    try:
        ds = _full_touch_dataset(8, 2, batch=4, window=2, n_windows=2)
        tr = _ctr_trainer(spec, sparse=True,
                          ps_address=("127.0.0.1", ps.port))
        out = tr.train(ds, shuffle=False)
        assert all(np.isfinite(np.asarray(leaf)).all()
                   for leaf in jax.tree.leaves(out.params))
        assert ps.num_updates > 0
    finally:
        ps.stop()
