"""Speculative decoding: the one invariant that matters is bit-identity
with the target model's own greedy decoding — for ANY draft model."""

import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models.base import Model
from distkeras_tpu.models.decode import generate
from distkeras_tpu.models.speculative import make_speculative_generate_fn
from distkeras_tpu.models.transformer import small_lm_spec


def _spec(layers=2, dim=32, **kw):
    cfg = dict(vocab_size=47, model_dim=dim, num_heads=2, num_layers=layers,
               max_seq_len=64)
    cfg.update(kw)
    spec = small_lm_spec(**cfg)
    spec.config["compute_dtype"] = "float32"
    return spec


@pytest.fixture(scope="module")
def target():
    return Model.init(_spec(layers=3, dim=48, num_heads=4), seed=0)


def test_matches_target_greedy_with_good_draft(target):
    """Draft = the target itself: every proposal accepted, output equal."""
    prompt = jnp.asarray([[5, 17, 3, 9]], jnp.int32)
    want = generate(target, prompt, max_new_tokens=12)
    fn = make_speculative_generate_fn(target.spec, target.spec, 12, k=4)
    got = fn(target.params, target.params, prompt)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_matches_target_greedy_with_unrelated_draft(target):
    """Draft = a differently-seeded small model: proposals mostly rejected,
    output STILL equal (correctness never depends on draft quality)."""
    draft = Model.init(_spec(layers=1, dim=32), seed=99)
    prompt = jnp.asarray([[40, 2, 21]], jnp.int32)
    want = generate(target, prompt, max_new_tokens=10)
    for k in (1, 3, 5):
        fn = make_speculative_generate_fn(target.spec, draft.spec, 10, k=k)
        got = fn(target.params, draft.params, prompt)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want),
                                      err_msg=f"k={k}")


def test_quantized_draft_still_exact(target):
    """int8 draft params: schedule changes, tokens don't."""
    from distkeras_tpu.ops.quantize import quantize_params

    draft = Model.init(_spec(layers=1, dim=32), seed=7)
    qd = quantize_params(draft.params, min_size=64)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    want = generate(target, prompt, max_new_tokens=8)
    fn = make_speculative_generate_fn(target.spec, draft.spec, 8, k=3)
    got = fn(target.params, qd, prompt)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_batched_matches_per_row_greedy(target):
    """Batched lockstep commit: every row of a batch-3 speculative decode
    equals that row's own plain greedy decode, for a good AND a bad
    draft (the batch-min prefix changes the schedule, never a token)."""
    prompt = jnp.asarray([[5, 17, 3, 9], [40, 2, 21, 1], [1, 1, 1, 1]],
                         jnp.int32)
    want = generate(target, prompt, max_new_tokens=10)
    for draft in (target, Model.init(_spec(layers=1, dim=32), seed=99)):
        fn = make_speculative_generate_fn(target.spec, draft.spec, 10, k=3,
                                          with_stats=True)
        got, iters = fn(target.params, draft.params, prompt)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert int(iters) >= 1
    # identical draft: every round accepts everything, so the batch run
    # takes exactly as few rounds as batch-1 would
    fn = make_speculative_generate_fn(target.spec, target.spec, 10, k=3,
                                      with_stats=True)
    _, iters = fn(target.params, target.params, prompt)
    assert int(iters) == -(-(10 - 1) // 4)  # ceil((n-1)/(k+1))


def test_guards(target):
    draft = _spec(layers=1)
    with pytest.raises(ValueError, match="vocab mismatch"):
        make_speculative_generate_fn(target.spec, _spec(vocab_size=13), 8)
    with pytest.raises(ValueError, match="k must be"):
        make_speculative_generate_fn(target.spec, draft, 8, k=0)
    fn = make_speculative_generate_fn(target.spec, draft, 8, k=2)
    with pytest.raises(ValueError, match="max_seq_len"):
        fn(target.params, Model.init(draft, seed=1).params,
           jnp.zeros((1, 60), jnp.int32))
