"""Speculative decoding: the one invariant that matters is bit-identity
with the target model's own greedy decoding — for ANY draft model."""

import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models.base import Model
from distkeras_tpu.models.decode import generate
from distkeras_tpu.models.speculative import make_speculative_generate_fn
from distkeras_tpu.models.transformer import small_lm_spec


def _spec(layers=2, dim=32, **kw):
    cfg = dict(vocab_size=47, model_dim=dim, num_heads=2, num_layers=layers,
               max_seq_len=64)
    cfg.update(kw)
    spec = small_lm_spec(**cfg)
    spec.config["compute_dtype"] = "float32"
    return spec


@pytest.fixture(scope="module")
def target():
    return Model.init(_spec(layers=3, dim=48, num_heads=4), seed=0)


def test_matches_target_greedy_with_good_draft(target):
    """Draft = the target itself: every proposal accepted, output equal."""
    prompt = jnp.asarray([[5, 17, 3, 9]], jnp.int32)
    want = generate(target, prompt, max_new_tokens=12)
    fn = make_speculative_generate_fn(target.spec, target.spec, 12, k=4)
    got = fn(target.params, target.params, prompt)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow  # tier-1 budget fix (PR 11): heaviest cells ride the full suite
def test_matches_target_greedy_with_unrelated_draft(target):
    """Draft = a differently-seeded small model: proposals mostly rejected,
    output STILL equal (correctness never depends on draft quality)."""
    draft = Model.init(_spec(layers=1, dim=32), seed=99)
    prompt = jnp.asarray([[40, 2, 21]], jnp.int32)
    want = generate(target, prompt, max_new_tokens=10)
    for k in (1, 3, 5):
        fn = make_speculative_generate_fn(target.spec, draft.spec, 10, k=k)
        got = fn(target.params, draft.params, prompt)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want),
                                      err_msg=f"k={k}")


def test_quantized_draft_still_exact(target):
    """int8 draft params: schedule changes, tokens don't."""
    from distkeras_tpu.ops.quantize import quantize_params

    draft = Model.init(_spec(layers=1, dim=32), seed=7)
    qd = quantize_params(draft.params, min_size=64)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    want = generate(target, prompt, max_new_tokens=8)
    fn = make_speculative_generate_fn(target.spec, draft.spec, 8, k=3)
    got = fn(target.params, qd, prompt)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow  # tier-1 budget fix (PR 11): heaviest cells ride the full suite
def test_quantized_kv_cache_matches_plain_quantized_decode(target):
    """quantize_cache speculative == plain decode with the SAME int8
    cache rounding: both attend over identically-quantized K/V rows, so
    the committed-token contract holds verbatim (the draft changes the
    schedule, never the math).  Also covers the rewound-row re-quantize
    path (uncommitted draft rows overwritten next round)."""
    from distkeras_tpu.models.decode import make_generate_fn

    draft = Model.init(_spec(layers=1, dim=32), seed=99)
    prompt = jnp.asarray([[40, 2, 21], [7, 7, 1]], jnp.int32)
    want = make_generate_fn(target.spec, 10, quantize_cache=True)(
        target.params, prompt)
    fn = make_speculative_generate_fn(target.spec, draft.spec, 10, k=3,
                                      quantize_cache=True)
    got = fn(target.params, draft.params, prompt)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # the fused draft step cannot serve an int8 cache: loud refusal
    import pytest
    with pytest.raises(ValueError, match="quantize_cache"):
        make_speculative_generate_fn(target.spec, draft.spec, 10, k=3,
                                     quantize_cache=True,
                                     draft_step_impl="fused")


@pytest.mark.slow  # tier-1 budget fix (PR 11): heaviest cells ride the full suite
def test_batched_matches_per_row_greedy(target):
    """Batched lockstep commit: every row of a batch-3 speculative decode
    equals that row's own plain greedy decode, for a good AND a bad
    draft (the batch-min prefix changes the schedule, never a token)."""
    prompt = jnp.asarray([[5, 17, 3, 9], [40, 2, 21, 1], [1, 1, 1, 1]],
                         jnp.int32)
    want = generate(target, prompt, max_new_tokens=10)
    for draft in (target, Model.init(_spec(layers=1, dim=32), seed=99)):
        fn = make_speculative_generate_fn(target.spec, draft.spec, 10, k=3,
                                          with_stats=True)
        got, iters = fn(target.params, draft.params, prompt)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert int(iters) >= 1
    # identical draft: every round accepts everything, so the batch run
    # takes exactly as few rounds as batch-1 would
    fn = make_speculative_generate_fn(target.spec, target.spec, 10, k=3,
                                      with_stats=True)
    _, iters = fn(target.params, target.params, prompt)
    assert int(iters) == -(-(10 - 1) // 4)  # ceil((n-1)/(k+1))


@pytest.mark.slow  # tier-1 budget fix (PR 11): heaviest cells ride the full suite
def test_eos_matches_plain_decode_and_exits_early(target):
    """EOS semantics equal make_generate_fn's exactly — EOS kept, pads
    after, per row — for eos ids that fire at different points (or never),
    with the good and the bad draft; and the loop exits early when every
    row finishes (iters shrinks vs the no-EOS run)."""
    prompt = jnp.asarray([[5, 17, 3, 9], [40, 2, 21, 1]], jnp.int32)
    plain = np.asarray(generate(target, prompt, max_new_tokens=12))
    # candidate eos ids: tokens the greedy decode actually emits early,
    # plus one that never appears
    eos_candidates = [int(plain[0, 0]), int(plain[1, 2]), 46]
    bad_draft = Model.init(_spec(layers=1, dim=32), seed=99)
    for eos in eos_candidates:
        want = np.asarray(generate(target, prompt, max_new_tokens=12,
                                   eos_id=eos, pad_id=45))
        for draft in (target, bad_draft):
            fn = make_speculative_generate_fn(target.spec, draft.spec, 12,
                                              k=3, eos_id=eos, pad_id=45)
            got = np.asarray(fn(target.params, draft.params, prompt))
            np.testing.assert_array_equal(got, want, err_msg=f"eos={eos}")

    # early exit MUST engage: duplicate row 0 so eos = its first emitted
    # token finishes every row in round 1, and assert the loop really
    # stopped early (a vacuous <= would pass with early exit broken)
    both = jnp.asarray(np.stack([np.asarray(prompt[0])] * 2))
    fn_all = make_speculative_generate_fn(target.spec, target.spec, 12, k=3,
                                          with_stats=True)
    _, iters_full = fn_all(target.params, target.params, both)
    eos_first = int(plain[0, 0])
    fn_eos = make_speculative_generate_fn(target.spec, target.spec, 12,
                                          k=3, eos_id=eos_first,
                                          with_stats=True)
    toks_eos, iters_eos = fn_eos(target.params, target.params, both)
    assert int(iters_eos) < int(iters_full), \
        f"early exit did not engage: {int(iters_eos)} vs {int(iters_full)}"
    # and the output still matches the plain decoder's EOS semantics
    want = np.asarray(generate(target, both, max_new_tokens=12,
                               eos_id=eos_first, pad_id=0))
    np.testing.assert_array_equal(np.asarray(toks_eos), want)


def test_speculative_accept_closed_form():
    """The accept/residual rule in its two analytic corners."""
    import jax

    from distkeras_tpu.models.speculative import speculative_accept

    V, k = 5, 3
    # identical distributions: every proposal accepted (u*q < p a.s.),
    # m == k, and the committed token is the bonus sample from p_t[k]
    p = jnp.asarray(np.full((k + 1, V), 1.0 / V, np.float32))
    q = p[:k]
    for seed in range(8):
        key = jax.random.PRNGKey(seed)
        drafted = jnp.asarray([1, 3, 0], jnp.int32)
        m, tok = speculative_accept(key, p, q, drafted)
        assert int(m) == k
        assert 0 <= int(tok) < V
    # disjoint supports: the draft proposes a token the target gives zero
    # mass -> immediate rejection (m == 0) and the residual IS p_t[0]
    p0 = np.zeros(V, np.float32)
    p0[2:] = 1.0 / 3
    pt = jnp.asarray(np.stack([p0] * (k + 1)))
    qd = np.zeros((k, V), np.float32)
    qd[:, 0] = 1.0
    toks = []
    for seed in range(64):
        m, tok = speculative_accept(jax.random.PRNGKey(seed), pt,
                                    jnp.asarray(qd), jnp.zeros(k, jnp.int32))
        assert int(m) == 0
        toks.append(int(tok))
    assert set(toks) <= {2, 3, 4}  # residual support == target support


def test_speculative_accept_exact_marginal():
    """The whole point of the scheme: the FIRST committed token's marginal
    equals the target distribution regardless of the draft, combining the
    accept path (drafted[0] kept) and the reject path (residual resample).
    20k vmapped trials; total-variation tolerance 0.02 (~3 sigma for this
    N and vocab)."""
    import jax

    from distkeras_tpu.models.speculative import speculative_accept

    V, k, N = 7, 3, 20000
    rng = np.random.default_rng(0)
    p_t = jnp.asarray(rng.dirichlet(np.ones(V), size=k + 1).astype(np.float32))
    p_d = jnp.asarray(rng.dirichlet(np.ones(V), size=k).astype(np.float32))

    def trial(key):
        kd, ka = jax.random.split(key)
        drafted = jax.vmap(
            lambda kk, q: jax.random.categorical(kk, jnp.log(q)))(
            jax.random.split(kd, k), p_d).astype(jnp.int32)
        m, tok = speculative_accept(ka, p_t, p_d, drafted)
        return jnp.where(m >= 1, drafted[0], tok)

    firsts = np.asarray(jax.vmap(trial)(jax.random.split(jax.random.PRNGKey(1), N)))
    emp = np.bincount(firsts, minlength=V) / N
    tv = 0.5 * np.abs(emp - np.asarray(p_t[0])).sum()
    assert tv < 0.02, f"TV {tv}: empirical {emp} vs target {np.asarray(p_t[0])}"


def test_sampling_generation_runs_and_is_seeded(target):
    """Speculative sampling end to end: valid tokens, deterministic per
    rng, different across rngs, batched and batch-1."""
    import jax

    draft = Model.init(_spec(layers=1, dim=32), seed=99)
    prompt = jnp.asarray([[5, 17, 3, 9], [1, 2, 3, 4]], jnp.int32)
    fn = make_speculative_generate_fn(target.spec, draft.spec, 10, k=3,
                                      temperature=0.8, with_stats=True)
    out1, it1 = fn(target.params, draft.params, prompt, jax.random.PRNGKey(0))
    out2, _ = fn(target.params, draft.params, prompt, jax.random.PRNGKey(0))
    out3, _ = fn(target.params, draft.params, prompt, jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert np.asarray(out1).shape == (2, 10)
    assert int(it1) >= 1
    a = np.asarray(out1)
    assert ((a >= 0) & (a < 47)).all()
    assert not np.array_equal(a, np.asarray(out3))  # rng actually used


def test_guards(target):
    draft = _spec(layers=1)
    with pytest.raises(ValueError, match="vocab mismatch"):
        make_speculative_generate_fn(target.spec, _spec(vocab_size=13), 8)
    with pytest.raises(ValueError, match="k must be"):
        make_speculative_generate_fn(target.spec, draft, 8, k=0)
    fn = make_speculative_generate_fn(target.spec, draft, 8, k=2)
    with pytest.raises(ValueError, match="max_seq_len"):
        fn(target.params, Model.init(draft, seed=1).params,
           jnp.zeros((1, 60), jnp.int32))


def test_fused_draft_steps_match_xla_draft_steps():
    """The fused Pallas draft path must commit exactly the XLA draft
    path's tokens (the target verify window is identical either way, so
    any divergence is a fused-step bug).  Needs a lane-tiled draft —
    model_dim 128 — and runs the kernel through the Pallas interpreter
    on CPU."""
    dspec = _spec(layers=2, dim=128, num_heads=2)
    tspec = _spec(layers=3, dim=128, num_heads=2)
    tgt = Model.init(tspec, seed=1)
    drf = Model.init(dspec, seed=2)
    prompt = jnp.asarray([[3, 14, 1]], jnp.int32)
    want = np.asarray(make_speculative_generate_fn(
        tspec, dspec, 10, k=3, draft_step_impl="xla")(
        tgt.params, drf.params, prompt))
    got = np.asarray(make_speculative_generate_fn(
        tspec, dspec, 10, k=3, draft_step_impl="fused")(
        tgt.params, drf.params, prompt))
    np.testing.assert_array_equal(got, want)


def test_fused_draft_rejects_unsupported_draft_shape(target):
    """dim-48 drafts are not lane-tiled: explicit 'fused' fails loudly,
    auto quietly uses the XLA step."""
    prompt = jnp.asarray([[5, 2]], jnp.int32)
    with pytest.raises(ValueError, match="fused"):
        make_speculative_generate_fn(
            target.spec, target.spec, 6, k=2, draft_step_impl="fused")(
            target.params, target.params, prompt)
    toks = make_speculative_generate_fn(target.spec, target.spec, 6, k=2)(
        target.params, target.params, prompt)
    assert np.asarray(toks).shape == (1, 6)
