"""Streaming inference service (reference: the Kafka pipeline, SURVEY §2.21)."""

import numpy as np
import pytest

from distkeras_tpu.models.base import Model, ModelSpec
from distkeras_tpu.runtime.streaming import (
    StreamingClient, StreamingInferenceServer, stream_predict)


@pytest.fixture(scope="module")
def served_model():
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 3},
                     input_shape=(6,))
    model = Model.init(spec, seed=0)
    server = StreamingInferenceServer(model, max_batch=32).start()
    yield model, server
    server.stop()


def test_stream_matches_direct_predict(served_model):
    model, server = served_model
    rng = np.random.default_rng(0)
    x = rng.normal(size=(20, 6)).astype(np.float32)  # < max_batch: padding path
    with StreamingClient("127.0.0.1", server.port) as client:
        assert client.max_batch == 32
        streamed = client.predict(x)
    direct = model.predict(x)
    np.testing.assert_allclose(streamed, direct, rtol=1e-5, atol=1e-6)


def test_many_micro_batches_one_connection(served_model):
    model, server = served_model
    rng = np.random.default_rng(1)
    with StreamingClient("127.0.0.1", server.port) as client:
        for b in (1, 7, 32, 5):  # varying sizes, no recompiles server-side
            x = rng.normal(size=(b, 6)).astype(np.float32)
            out = client.predict(x)
            assert out.shape == (b, 3)
            np.testing.assert_allclose(out, model.predict(x), rtol=1e-5, atol=1e-6)


def test_stream_predict_pipeline(served_model):
    model, server = served_model
    rng = np.random.default_rng(2)
    rows = rng.normal(size=(50, 6)).astype(np.float32)
    got_rows, got_preds = [], []
    for r, p in stream_predict("127.0.0.1", server.port, iter(rows), micro_batch=16):
        got_rows.append(r)
        got_preds.append(p)
    # 50 events at micro_batch 16 -> 16+16+16+2 (tail flushed)
    assert [len(r) for r in got_rows] == [16, 16, 16, 2]
    np.testing.assert_allclose(np.concatenate(got_rows), rows)
    np.testing.assert_allclose(np.concatenate(got_preds), model.predict(rows),
                               rtol=1e-5, atol=1e-6)


def test_oversized_batch_rejected(served_model):
    _, server = served_model
    with StreamingClient("127.0.0.1", server.port) as client:
        with pytest.raises(ValueError, match="outside"):
            client.predict(np.zeros((33, 6), np.float32))


def test_wrong_row_shape_rejected(served_model):
    _, server = served_model
    with StreamingClient("127.0.0.1", server.port) as client:
        with pytest.raises(ValueError, match="server expects"):
            client.predict(np.zeros((4, 5), np.float32))


def test_concurrent_clients(served_model):
    import threading

    model, server = served_model
    rng = np.random.default_rng(3)
    xs = [rng.normal(size=(8, 6)).astype(np.float32) for _ in range(4)]
    outs = [None] * 4
    errs = []

    def go(i):
        try:
            with StreamingClient("127.0.0.1", server.port) as c:
                outs[i] = c.predict(xs[i])
        except BaseException as e:
            errs.append(e)

    threads = [threading.Thread(target=go, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    for i in range(4):
        np.testing.assert_allclose(outs[i], model.predict(xs[i]), rtol=1e-5, atol=1e-6)
