"""Text preprocessing (Tokenizer / pad_sequences) and the text -> LSTM
pipeline end-to-end."""

import numpy as np
import pytest

from distkeras_tpu.data.text import Tokenizer, pad_sequences


def test_tokenizer_basic_ranking_and_reserved_zero():
    tok = Tokenizer().fit_on_texts(["the cat sat", "the cat ran", "the dog"])
    # 'the' most frequent -> index 1 (0 reserved for padding)
    assert tok.word_index["the"] == 1
    assert tok.word_index["cat"] == 2
    seqs = tok.texts_to_sequences(["the cat", "dog the"])
    assert seqs[0] == [1, 2]
    assert 0 not in {i for s in seqs for i in s}
    assert tok.vocab_size == max(tok.word_index.values()) + 1


def test_tokenizer_filters_lower_and_oov():
    tok = Tokenizer(oov_token="<oov>").fit_on_texts(["Hello, World! hello?"])
    assert tok.word_index["<oov>"] == 1
    assert tok.word_index["hello"] == 2  # case-folded, punctuation stripped
    assert tok.texts_to_sequences(["hello UNSEEN world"])[0] == [2, 1, 3]
    # without oov, unseen words drop
    tok2 = Tokenizer().fit_on_texts(["a b"])
    assert tok2.texts_to_sequences(["a zzz b"])[0] == [1, 2] or \
        tok2.texts_to_sequences(["a zzz b"])[0] == [2, 1]


def test_num_words_caps_encoding():
    texts = ["a a a b b c"]
    tok = Tokenizer(num_words=3).fit_on_texts(texts)
    # vocab capped at indices < 3: 'a'->1, 'b'->2 survive, 'c'->3 dropped
    assert tok.texts_to_sequences(texts)[0] == [1, 1, 1, 2, 2]
    assert tok.vocab_size == 3


def test_tokenizer_json_roundtrip():
    tok = Tokenizer(num_words=10, oov_token="<oov>").fit_on_texts(
        ["one two two three three three"])
    tok2 = Tokenizer.from_json(tok.to_json())
    assert tok2.word_index == tok.word_index
    texts = ["three unseen one"]
    assert tok2.texts_to_sequences(texts) == tok.texts_to_sequences(texts)


def test_filters_are_literal_characters_not_regex():
    # '*-+' as a regex class is a bad range; as literal chars it's fine
    tok = Tokenizer(filters="*-+").fit_on_texts(["a*b-c+d e"])
    assert set(tok.word_index) == {"a", "b", "c", "d", "e"}


def test_oov_token_in_corpus_keeps_index_one():
    tok = Tokenizer(oov_token="unk").fit_on_texts(["unk unk unk word"])
    assert tok.word_index["unk"] == 1
    # a word NEVER ranks into index 1
    assert sorted(tok.word_index.values()) == sorted(set(tok.word_index.values()))
    assert tok.texts_to_sequences(["unseen"])[0] == [1]


def test_empty_corpus_oov_roundtrip():
    tok = Tokenizer(oov_token="<oov>").fit_on_texts([])
    t2 = Tokenizer.from_json(tok.to_json())
    assert t2.texts_to_sequences(["anything"]) == tok.texts_to_sequences(["anything"]) == [[1]]


def test_pad_sequences_maxlen_zero():
    assert pad_sequences([[1, 2]], maxlen=0).shape == (1, 0)


def test_pad_sequences_semantics():
    seqs = [[1, 2, 3], [4], []]
    np.testing.assert_array_equal(
        pad_sequences(seqs, maxlen=4),
        [[0, 1, 2, 3], [0, 0, 0, 4], [0, 0, 0, 0]])
    np.testing.assert_array_equal(
        pad_sequences(seqs, maxlen=2, padding="post", truncating="post"),
        [[1, 2], [4, 0], [0, 0]])
    # pre-truncation keeps the TAIL
    np.testing.assert_array_equal(pad_sequences([[1, 2, 3, 4]], maxlen=2),
                                  [[3, 4]])
    assert pad_sequences([], maxlen=3).shape == (0, 3)
    with pytest.raises(ValueError, match="pre.*post|'pre' or 'post'"):
        pad_sequences(seqs, padding="left")


def test_text_to_lstm_pipeline_learns():
    """Raw text -> Tokenizer -> pad_sequences -> Dataset -> LSTM trainer:
    the full Keras-era sentiment-style pipeline, on a separable toy task
    (class = whether 'good' or 'bad' appears)."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.rnn import lstm_classifier_spec
    from distkeras_tpu.trainers import SingleTrainer

    rng = np.random.default_rng(0)
    fillers = ["movie", "film", "plot", "acting", "scene", "it", "was", "very"]
    texts, labels = [], []
    for _ in range(256):
        words = list(rng.choice(fillers, size=6))
        lab = int(rng.integers(0, 2))
        words.insert(int(rng.integers(0, len(words))), "good" if lab else "bad")
        texts.append(" ".join(words))
        labels.append(lab)
    tok = Tokenizer().fit_on_texts(texts)
    x = pad_sequences(tok.texts_to_sequences(texts), maxlen=8)
    y = np.eye(2, dtype=np.float32)[labels]
    spec = lstm_classifier_spec(vocab_size=tok.vocab_size, seq_len=8,
                                embed_dim=16, hidden_sizes=(32,), num_outputs=2)
    tr = SingleTrainer(spec, worker_optimizer="adam", learning_rate=3e-3,
                       batch_size=32, num_epoch=12, seed=1)
    model = tr.train(Dataset({"features": x, "label": y}))
    pred = np.argmax(model.predict(x), axis=1)
    assert (pred == np.asarray(labels)).mean() > 0.95
