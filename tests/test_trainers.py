"""Trainer integration tests on the simulated 8-chip slice (SURVEY §4.2/4.3)."""

import jax
import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.evaluators import AccuracyEvaluator
from distkeras_tpu.models.base import Model, ModelSpec
from distkeras_tpu.predictors import ModelPredictor
from distkeras_tpu.trainers import (
    ADAG,
    AEASGD,
    DOWNPOUR,
    AveragingTrainer,
    DynSGD,
    EAMSGD,
    EnsembleTrainer,
    SingleTrainer,
)


def tiny_mlp_spec():
    return ModelSpec(name="mlp", config={"hidden_sizes": (32,), "num_outputs": 2}, input_shape=(8,))


def accuracy_of(model, dataset):
    ds = ModelPredictor(model, features_col="features").predict(dataset)
    return AccuracyEvaluator(prediction_col="prediction", label_col="label_index").evaluate(ds)


def test_single_trainer_learns(toy_dataset):
    trainer = SingleTrainer(tiny_mlp_spec(), loss="categorical_crossentropy",
                            worker_optimizer="sgd", learning_rate=0.1,
                            batch_size=64, num_epoch=5)
    model = trainer.train(toy_dataset)
    assert trainer.history[-1] < trainer.history[0]
    assert accuracy_of(model, toy_dataset) > 0.95
    assert trainer.get_training_time() > 0


@pytest.mark.parametrize("trainer_cls,kwargs", [
    (ADAG, {"communication_window": 2}),
    (DOWNPOUR, {"communication_window": 4, "learning_rate": 0.01}),
    (AEASGD, {"communication_window": 4, "rho": 1.0}),
    (EAMSGD, {"communication_window": 4, "rho": 1.0, "momentum": 0.9}),
    (DynSGD, {"communication_window": 2}),
])
def test_distributed_trainers_learn(toy_dataset, trainer_cls, kwargs):
    kwargs = dict(kwargs)
    kwargs.setdefault("learning_rate", 0.05)
    trainer = trainer_cls(tiny_mlp_spec(), loss="categorical_crossentropy",
                          worker_optimizer=kwargs.pop("worker_optimizer", "sgd"),
                          num_workers=8, batch_size=8, num_epoch=4, **kwargs)
    model = trainer.train(toy_dataset)
    assert accuracy_of(model, toy_dataset) > 0.9, f"{trainer_cls.__name__} failed to learn"


def test_adag_window1_matches_large_batch_sgd(toy_dataset):
    """ADAG with window=1 is exactly large-batch SGD: center' =
    center − lr · mean_r grad_r — must match a single-device run on the
    same global batches (the sync-equivalence anchor for the collectives)."""
    lr, bs, workers = 0.1, 16, 8
    single = SingleTrainer(tiny_mlp_spec(), loss="categorical_crossentropy",
                           worker_optimizer="sgd", learning_rate=lr,
                           batch_size=bs * workers, num_epoch=1, seed=0)
    m_single = single.train(toy_dataset, shuffle=False)

    adag = ADAG(tiny_mlp_spec(), loss="categorical_crossentropy",
                worker_optimizer="sgd", learning_rate=lr, num_workers=workers,
                batch_size=bs, communication_window=1, num_epoch=1, seed=0)
    m_adag = adag.train(toy_dataset, shuffle=False)

    for a, b in zip(jax.tree.leaves(m_single.params), jax.tree.leaves(m_adag.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_averaging_trainer(toy_dataset):
    trainer = AveragingTrainer(tiny_mlp_spec(), loss="categorical_crossentropy",
                               learning_rate=0.1, num_workers=8, batch_size=8, num_epoch=3)
    model = trainer.train(toy_dataset)
    assert accuracy_of(model, toy_dataset) > 0.9


def test_ensemble_trainer_returns_n_distinct_models(toy_dataset):
    trainer = EnsembleTrainer(tiny_mlp_spec(), loss="categorical_crossentropy",
                              learning_rate=0.1, num_workers=8, batch_size=8, num_epoch=2)
    models = trainer.train(toy_dataset)
    assert len(models) == 8
    p0 = jax.tree.leaves(models[0].params)[0]
    p1 = jax.tree.leaves(models[1].params)[0]
    assert not np.allclose(np.asarray(p0), np.asarray(p1))
    assert accuracy_of(models[0], toy_dataset) > 0.85


def test_determinism_same_seed_same_result(toy_dataset):
    """Sync path determinism (SURVEY §5 race-detection replacement)."""
    def run():
        t = ADAG(tiny_mlp_spec(), loss="categorical_crossentropy", learning_rate=0.05,
                 num_workers=8, batch_size=8, communication_window=2, num_epoch=1, seed=123)
        return t.train(toy_dataset)

    m1, m2 = run(), run()
    for a, b in zip(jax.tree.leaves(m1.params), jax.tree.leaves(m2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_metrics_recorded_single_and_distributed(toy_dataset):
    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.trainers import ADAG, SingleTrainer

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 2},
                     input_shape=(8,))
    for cls, kw in ((SingleTrainer, {}), (ADAG, {"num_workers": 2, "communication_window": 2})):
        t = cls(spec, loss="categorical_crossentropy", batch_size=16, num_epoch=2, **kw)
        t.train(toy_dataset)
        assert len(t.metrics) == 2
        for rec in t.metrics:
            assert rec["samples"] > 0 and rec["seconds"] > 0
            assert rec["samples_per_sec_per_chip"] > 0
        # every sample fed is accounted for exactly once per epoch
        assert t.metrics[0]["samples"] <= len(toy_dataset)


def test_profile_dir_writes_trace(toy_dataset, tmp_path):
    import os

    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.trainers import SingleTrainer

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 2},
                     input_shape=(8,))
    t = SingleTrainer(spec, loss="categorical_crossentropy", batch_size=16,
                      num_epoch=1, profile_dir=str(tmp_path / "prof"))
    t.train(toy_dataset)
    files = [os.path.join(dp, f) for dp, _, fs in os.walk(tmp_path / "prof") for f in fs]
    assert files, "profiler trace directory is empty"


def test_async_rejects_non_float32_params():
    import jax
    import numpy as np
    import pytest as _pytest

    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.runtime.async_trainer import AsyncDOWNPOUR

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 2},
                     input_shape=(4,))
    m = Model.init(spec, seed=0)
    m = Model(spec=spec, params=jax.tree.map(lambda x: x.astype("bfloat16"), m.params))
    ds = Dataset({"features": np.zeros((64, 4), np.float32),
                  "label": np.eye(2, dtype=np.float32)[np.zeros(64, int)]})
    t = AsyncDOWNPOUR(m, num_workers=1, batch_size=16, num_epoch=1)
    with _pytest.raises(TypeError, match="float32"):
        t.train(ds)
