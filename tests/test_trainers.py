"""Trainer integration tests on the simulated 8-chip slice (SURVEY §4.2/4.3)."""

import jax
import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.evaluators import AccuracyEvaluator
from distkeras_tpu.models.base import Model, ModelSpec
from distkeras_tpu.predictors import ModelPredictor
from distkeras_tpu.trainers import (
    ADAG,
    AEASGD,
    DOWNPOUR,
    AveragingTrainer,
    DynSGD,
    EAMSGD,
    EnsembleTrainer,
    SingleTrainer,
)


def tiny_mlp_spec():
    return ModelSpec(name="mlp", config={"hidden_sizes": (32,), "num_outputs": 2}, input_shape=(8,))


def accuracy_of(model, dataset):
    ds = ModelPredictor(model, features_col="features").predict(dataset)
    return AccuracyEvaluator(prediction_col="prediction", label_col="label_index").evaluate(ds)


def test_single_trainer_learns(toy_dataset):
    trainer = SingleTrainer(tiny_mlp_spec(), loss="categorical_crossentropy",
                            worker_optimizer="sgd", learning_rate=0.1,
                            batch_size=64, num_epoch=5)
    model = trainer.train(toy_dataset)
    assert trainer.history[-1] < trainer.history[0]
    assert accuracy_of(model, toy_dataset) > 0.95
    assert trainer.get_training_time() > 0


@pytest.mark.parametrize("trainer_cls,kwargs", [
    (ADAG, {"communication_window": 2}),
    (DOWNPOUR, {"communication_window": 4, "learning_rate": 0.01}),
    (AEASGD, {"communication_window": 4, "rho": 1.0}),
    (EAMSGD, {"communication_window": 4, "rho": 1.0, "momentum": 0.9}),
    (DynSGD, {"communication_window": 2}),
])
def test_distributed_trainers_learn(toy_dataset, trainer_cls, kwargs):
    kwargs = dict(kwargs)
    kwargs.setdefault("learning_rate", 0.05)
    trainer = trainer_cls(tiny_mlp_spec(), loss="categorical_crossentropy",
                          worker_optimizer=kwargs.pop("worker_optimizer", "sgd"),
                          num_workers=8, batch_size=8, num_epoch=4, **kwargs)
    model = trainer.train(toy_dataset)
    assert accuracy_of(model, toy_dataset) > 0.9, f"{trainer_cls.__name__} failed to learn"


def test_adag_window1_matches_large_batch_sgd(toy_dataset):
    """ADAG with window=1 is exactly large-batch SGD: center' =
    center − lr · mean_r grad_r — must match a single-device run on the
    same global batches (the sync-equivalence anchor for the collectives)."""
    lr, bs, workers = 0.1, 16, 8
    single = SingleTrainer(tiny_mlp_spec(), loss="categorical_crossentropy",
                           worker_optimizer="sgd", learning_rate=lr,
                           batch_size=bs * workers, num_epoch=1, seed=0)
    m_single = single.train(toy_dataset, shuffle=False)

    adag = ADAG(tiny_mlp_spec(), loss="categorical_crossentropy",
                worker_optimizer="sgd", learning_rate=lr, num_workers=workers,
                batch_size=bs, communication_window=1, num_epoch=1, seed=0)
    m_adag = adag.train(toy_dataset, shuffle=False)

    for a, b in zip(jax.tree.leaves(m_single.params), jax.tree.leaves(m_adag.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_averaging_trainer(toy_dataset):
    trainer = AveragingTrainer(tiny_mlp_spec(), loss="categorical_crossentropy",
                               learning_rate=0.1, num_workers=8, batch_size=8, num_epoch=3)
    model = trainer.train(toy_dataset)
    assert accuracy_of(model, toy_dataset) > 0.9


def test_ensemble_trainer_returns_n_distinct_models(toy_dataset):
    trainer = EnsembleTrainer(tiny_mlp_spec(), loss="categorical_crossentropy",
                              learning_rate=0.1, num_workers=8, batch_size=8, num_epoch=2)
    models = trainer.train(toy_dataset)
    assert len(models) == 8
    p0 = jax.tree.leaves(models[0].params)[0]
    p1 = jax.tree.leaves(models[1].params)[0]
    assert not np.allclose(np.asarray(p0), np.asarray(p1))
    assert accuracy_of(models[0], toy_dataset) > 0.85


def test_determinism_same_seed_same_result(toy_dataset):
    """Sync path determinism (SURVEY §5 race-detection replacement)."""
    def run():
        t = ADAG(tiny_mlp_spec(), loss="categorical_crossentropy", learning_rate=0.05,
                 num_workers=8, batch_size=8, communication_window=2, num_epoch=1, seed=123)
        return t.train(toy_dataset)

    m1, m2 = run(), run()
    for a, b in zip(jax.tree.leaves(m1.params), jax.tree.leaves(m2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_metrics_recorded_single_and_distributed(toy_dataset):
    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.trainers import ADAG, SingleTrainer

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 2},
                     input_shape=(8,))
    for cls, kw in ((SingleTrainer, {}), (ADAG, {"num_workers": 2, "communication_window": 2})):
        t = cls(spec, loss="categorical_crossentropy", batch_size=16, num_epoch=2, **kw)
        t.train(toy_dataset)
        assert len(t.metrics) == 2
        for rec in t.metrics:
            assert rec["samples"] > 0 and rec["seconds"] > 0
            assert rec["samples_per_sec_per_chip"] > 0
        # every sample fed is accounted for exactly once per epoch
        assert t.metrics[0]["samples"] <= len(toy_dataset)


@pytest.mark.slow  # tier-1 budget (ISSUE 14 satellite): 22.6 s, the single heaviest tier-1 cell: full jax profiler trace of a training run
def test_profile_dir_writes_trace(toy_dataset, tmp_path):
    import os

    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.trainers import SingleTrainer

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 2},
                     input_shape=(8,))
    t = SingleTrainer(spec, loss="categorical_crossentropy", batch_size=16,
                      num_epoch=1, profile_dir=str(tmp_path / "prof"))
    t.train(toy_dataset)
    files = [os.path.join(dp, f) for dp, _, fs in os.walk(tmp_path / "prof") for f in fs]
    assert files, "profiler trace directory is empty"


def test_async_rejects_non_float32_params():
    import jax
    import numpy as np
    import pytest as _pytest

    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.runtime.async_trainer import AsyncDOWNPOUR

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (8,), "num_outputs": 2},
                     input_shape=(4,))
    m = Model.init(spec, seed=0)
    m = Model(spec=spec, params=jax.tree.map(lambda x: x.astype("bfloat16"), m.params))
    ds = Dataset({"features": np.zeros((64, 4), np.float32),
                  "label": np.eye(2, dtype=np.float32)[np.zeros(64, int)]})
    t = AsyncDOWNPOUR(m, num_workers=1, batch_size=16, num_epoch=1)
    with _pytest.raises(TypeError, match="float32"):
        t.train(ds)


def test_validation_data_records_per_epoch_metrics():
    import numpy as _np

    from distkeras_tpu.data.dataset import Dataset as _DS
    from distkeras_tpu.models.base import ModelSpec as _MS
    from distkeras_tpu.trainers import ADAG as _ADAG, SingleTrainer as _ST

    rng = _np.random.default_rng(0)
    x = rng.normal(size=(128, 8)).astype(_np.float32)
    w = rng.normal(size=(8, 3)).astype(_np.float32)
    labels = _np.argmax(x @ w, axis=1)
    onehot = _np.eye(3, dtype=_np.float32)[labels]
    train = _DS({"features": x[:96], "label": onehot[:96]})
    val = _DS({"features": x[96:], "label": onehot[96:]})
    spec = _MS(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 3},
               input_shape=(8,))

    tr = _ST(spec, batch_size=32, num_epoch=3, learning_rate=0.1)
    tr.train(train, validation_data=val)
    assert len(tr.metrics) == 3
    assert all("val_loss" in m and "val_accuracy" in m for m in tr.metrics)
    # training on a separable task: val accuracy must improve over random
    assert tr.metrics[-1]["val_accuracy"] > 0.5
    assert tr.metrics[-1]["val_loss"] < tr.metrics[0]["val_loss"]

    tr2 = _ADAG(spec, num_workers=8, batch_size=4, num_epoch=2,
                communication_window=2, learning_rate=0.1)
    tr2.train(train, validation_data=val)
    assert all("val_loss" in m for m in tr2.metrics)

    # regression labels (float vector targets): loss only, no accuracy
    reg = _DS({"features": x[:96], "label": (x[:96] @ w).astype(_np.float32)})
    regval = _DS({"features": x[96:], "label": (x[96:] @ w).astype(_np.float32)})
    spec_r = _MS(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 3},
                 input_shape=(8,))
    tr3 = _ST(spec_r, loss="mse", batch_size=32, num_epoch=1, learning_rate=0.01)
    tr3.train(reg, validation_data=regval)
    assert "val_loss" in tr3.metrics[-1]
    assert "val_accuracy" not in tr3.metrics[-1]

    # (N, 1) integer index labels must not argmax-collapse to class 0
    idx = _DS({"features": x[:96], "label": labels[:96].reshape(-1, 1)})
    idxval = _DS({"features": x[96:], "label": labels[96:].reshape(-1, 1)})
    tr4 = _ST(spec, loss="sparse_categorical_crossentropy",
              batch_size=32, num_epoch=3, learning_rate=0.1)
    # sparse CE wants [N] int labels; reshape col inside a wrapper loss
    import jax.numpy as _jnp
    from distkeras_tpu.ops.losses import get_loss as _gl
    sce = _gl("sparse_categorical_crossentropy")
    tr4.loss = lambda logits, y: sce(logits, y.reshape(-1))
    tr4.train(idx, validation_data=idxval)
    assert tr4.metrics[-1]["val_accuracy"] > 0.5

    # averaging trainer validates the averaged model; ensemble refuses
    from distkeras_tpu.trainers import AveragingTrainer as _AT, EnsembleTrainer as _ET
    tr5 = _AT(spec, num_workers=8, batch_size=4, num_epoch=1, learning_rate=0.1)
    tr5.train(train, validation_data=val)
    assert "val_accuracy" in tr5.metrics[-1]
    with pytest.raises(ValueError, match="ambiguous"):
        _ET(spec, num_workers=8, batch_size=4, num_epoch=1).train(
            train, validation_data=val)

    # token-level (B, T) int labels: accuracy counts tokens, not rows
    from distkeras_tpu.models.transformer import small_lm_spec as _lm
    lm_spec = _lm(vocab_size=16, model_dim=16, num_heads=2, num_layers=1,
                  max_seq_len=8)
    lm_spec.config["compute_dtype"] = "float32"
    toks = rng.integers(0, 16, (32, 8)).astype(_np.int32)
    tgts = _np.roll(toks, -1, axis=1).astype(_np.int32)
    lm_ds = _DS({"features": toks, "label": tgts})
    tr6 = _ST(lm_spec, loss=lambda logits, y: _optax_sce(logits, y),
              batch_size=8, num_epoch=1, learning_rate=0.01)
    tr6.train(lm_ds, validation_data=lm_ds)
    assert 0.0 <= tr6.metrics[-1]["val_accuracy"] <= 1.0

    # empty validation set is a loud error, not a fake perfect score
    with pytest.raises(ValueError, match="empty"):
        _ST(spec, batch_size=32, num_epoch=1).train(
            train, validation_data=_DS({"features": x[:0], "label": onehot[:0]}))


def _optax_sce(logits, y):
    import jax.numpy as _jnp
    import optax as _optax

    return _optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(_jnp.float32), y).mean()


def test_single_trainer_early_stopping_stops_and_restores(toy_dataset):
    # an impossible min_delta means epoch 0 sets the best and every later
    # epoch is "no improvement": patience=1 stops at epoch 2 of 10
    trainer = SingleTrainer(tiny_mlp_spec(), loss="categorical_crossentropy",
                            worker_optimizer="sgd", learning_rate=0.1,
                            batch_size=64, num_epoch=10)
    model = trainer.train(toy_dataset, validation_data=toy_dataset,
                          early_stopping={"patience": 2, "min_delta": 1e9,
                                          "monitor": "val_loss"})
    assert len(trainer.metrics) == 3  # epoch 0 best + 2 stale (Keras >=)
    # restore_best hands back the epoch-0 weights: retraining one epoch
    # from them must reproduce epoch 1's val_loss trajectory start
    assert model is not None


def test_single_trainer_early_stopping_needs_validation(toy_dataset):
    trainer = SingleTrainer(tiny_mlp_spec(), loss="categorical_crossentropy",
                            worker_optimizer="sgd", learning_rate=0.1,
                            batch_size=64, num_epoch=3)
    with pytest.raises(ValueError, match="validation_data"):
        # pre-flight: must fail BEFORE any epoch trains
        trainer.train(toy_dataset, early_stopping={"patience": 0})
    assert len(trainer.metrics) == 0


def test_distributed_trainer_early_stopping(toy_dataset):
    trainer = ADAG(tiny_mlp_spec(), loss="categorical_crossentropy",
                   worker_optimizer="sgd", learning_rate=0.05,
                   num_workers=8, batch_size=8, num_epoch=10,
                   communication_window=2)
    model = trainer.train(toy_dataset, validation_data=toy_dataset,
                          early_stopping={"patience": 0, "min_delta": 1e9,
                                          "monitor": "val_loss"})
    assert len(trainer.metrics) == 2  # epoch 0 best, epoch 1 stops
    # restore_best: returned model is the epoch-0 center snapshot
    assert model.params is not None


def test_ensemble_rejects_early_stopping(toy_dataset):
    trainer = EnsembleTrainer(tiny_mlp_spec(), loss="categorical_crossentropy",
                              worker_optimizer="sgd", learning_rate=0.05,
                              num_workers=4, batch_size=8, num_epoch=2)
    with pytest.raises(ValueError, match="ambiguous for an ensemble"):
        trainer.train(toy_dataset, early_stopping={"patience": 1})


def test_accuracy_evaluator_rejects_integer_onehot():
    # integer arrays are always class indices; an int one-hot column must
    # raise with guidance, not broadcast into a wrong accuracy
    ds = Dataset({"prediction_index": np.array([0, 1, 1, 0]),
                  "label": np.eye(2, dtype=np.int64)[[0, 1, 0, 1]]})
    ev = AccuracyEvaluator(prediction_col="prediction_index", label_col="label")
    with pytest.raises(ValueError, match="Integer label"):
        ev.evaluate(ds)


def test_async_elastic_rejects_schedule_learning_rate():
    import optax

    from distkeras_tpu.runtime.async_trainer import AsyncAEASGD, AsyncEAMSGD

    sched = optax.exponential_decay(0.1, 10, 0.9)
    for cls in (AsyncAEASGD, AsyncEAMSGD):
        with pytest.raises(ValueError, match="scalar learning_rate"):
            cls(tiny_mlp_spec(), loss="categorical_crossentropy",
                num_workers=2, learning_rate=sched)


def test_engine_steady_state_rate_preserves_state(toy_dataset):
    """steady_state_rate compiles a multi-epoch program, reports a positive
    rate, and must NOT consume the caller's state (the epoch program
    donates its inputs; the method copies internally)."""
    trainer = ADAG(tiny_mlp_spec(), loss="categorical_crossentropy",
                   worker_optimizer="sgd", learning_rate=0.05,
                   num_workers=8, batch_size=8, num_epoch=1,
                   communication_window=2)
    trainer.train(toy_dataset)
    engine = trainer.engine
    state = engine.init_state(trainer.model)
    chunk = next(iter(toy_dataset.chunked_epoch(
        64, ["features", "label"], window=2, chunk_windows=2)))
    rate = engine.steady_state_rate(state, chunk["features"], chunk["label"],
                                    reps=2, repeat=2)
    assert rate > 0
    # the caller's state is still alive and usable afterwards
    state2, losses = engine.run_epoch(state, chunk["features"], chunk["label"])
    assert np.isfinite(losses).all()
