"""Transport-parity tests (issue 3): ``transport="inproc"`` and
``transport="socket"`` must produce IDENTICAL training trajectories.

The inproc client executes its pull/commit at the exact program points the
socket client *sends* at, and both paths run the same center arithmetic
under the same hub lock — so for a deterministic schedule (one worker) the
trajectories are bit-equal, pipelined or serial, compressed or not.  These
tests pin that property; if it breaks, the inproc fast path has silently
become a different algorithm.
"""

import numpy as np
import pytest

from distkeras_tpu import observability as obs
from distkeras_tpu.models.base import Model, ModelSpec


def _mlp_spec():
    return ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))


def _train(trainer_name, toy_dataset, *, transport, pipeline, num_workers=1,
           **extra):
    import distkeras_tpu as dk

    cls = getattr(dk, trainer_name)
    trainer = cls(Model.init(_mlp_spec(), seed=0),
                  loss="categorical_crossentropy", batch_size=16, num_epoch=2,
                  num_workers=num_workers, communication_window=4,
                  learning_rate=0.05, seed=0, transport=transport,
                  pipeline=pipeline, **extra)
    model = trainer.train(toy_dataset)
    return trainer, model


def _assert_bit_identical(run_a, run_b):
    import jax

    (tr_a, m_a), (tr_b, m_b) = run_a, run_b
    assert tr_a.history == tr_b.history, "window-loss trajectories diverged"
    for a, b in zip(jax.tree.leaves(m_a.params), jax.tree.leaves(m_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("trainer_name,pipeline,extra", [
    ("AsyncADAG", True, {}),
    ("AsyncADAG", False, {}),
    pytest.param("AsyncAEASGD", True, {"rho": 2.0}, marks=pytest.mark.slow),
])
def test_inproc_matches_socket_bit_identical(trainer_name, pipeline, extra,
                                             toy_dataset):
    """Single-worker ADAG/AEASGD trajectories are bit-equal across
    transports, with and without the pipelined overlap."""
    sock = _train(trainer_name, toy_dataset, transport="socket",
                  pipeline=pipeline, **extra)
    inproc = _train(trainer_name, toy_dataset, transport="inproc",
                    pipeline=pipeline, **extra)
    _assert_bit_identical(sock, inproc)


@pytest.mark.slow  # full-suite coverage; tier-1 keeps the f32 parity pins
def test_inproc_matches_socket_with_int8_commits(toy_dataset):
    """The inproc client round-trips commits through the SAME quantize/
    dequantize + error-feedback math the wire uses, so compressed runs
    stay trajectory-identical too."""
    sock = _train("AsyncADAG", toy_dataset, transport="socket", pipeline=True,
                  compress_commits="int8")
    inproc = _train("AsyncADAG", toy_dataset, transport="inproc", pipeline=True,
                    compress_commits="int8")
    _assert_bit_identical(sock, inproc)


@pytest.mark.slow  # full-suite coverage; tier-1 keeps the f32 parity pins
def test_inproc_multiworker_learns(toy_dataset):
    """inproc with real worker concurrency end to end: 4 workers race
    commit_direct under the hub lock and the center still learns."""
    from distkeras_tpu.data.transformers import LabelIndexTransformer
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.predictors import ModelPredictor

    trainer, model = _train("AsyncADAG", toy_dataset, transport="inproc",
                            pipeline=True, num_workers=4)
    assert trainer.parameter_server.num_updates > 0
    ds = ModelPredictor(model, features_col="features").predict(toy_dataset)
    ds = LabelIndexTransformer().transform(ds)
    acc = AccuracyEvaluator(prediction_col="prediction_index",
                            label_col="label_index").evaluate(ds)
    assert acc > 0.9, f"inproc AsyncADAG accuracy {acc}"


def test_inproc_rejects_worker_only_mode():
    import distkeras_tpu as dk

    with pytest.raises(ValueError, match="inproc"):
        dk.AsyncADAG(_mlp_spec(), transport="inproc",
                     ps_address=("head", 4242))
    with pytest.raises(ValueError, match="transport"):
        dk.AsyncADAG(_mlp_spec(), transport="carrier-pigeon")


# -- shared-memory transport (ISSUE 18) ----------------------------------------

@pytest.mark.parametrize("pipeline", [True, False])
def test_shm_matches_socket_bit_identical(pipeline, toy_dataset):
    """transport="shm" carries the SAME framed bytes over mmap rings, so
    single-worker trajectories are bit-equal to socket runs.  The counter
    assertion guards against the attach silently declining — a run that
    degraded to TCP would pass the parity check vacuously."""
    obs.reset()
    obs.enable()
    try:
        shm = _train("AsyncADAG", toy_dataset, transport="shm",
                     pipeline=pipeline)
        counters = obs.snapshot()["counters"]
        assert counters.get("ps.shm_frames_total", 0) > 0, \
            "shm run silently fell back to TCP"
    finally:
        obs.disable()
        obs.reset()
    sock = _train("AsyncADAG", toy_dataset, transport="socket",
                  pipeline=pipeline)
    _assert_bit_identical(sock, shm)


@pytest.mark.slow  # full-suite coverage; tier-1 keeps the f32 parity pins
def test_shm_matches_socket_with_int8_commits(toy_dataset):
    """Quantized commits cross the rings bit-identically too, and a
    batched-receive hub (recv_batch_depth) changes syscall shape only —
    all three runs land on the same trajectory."""
    sock = _train("AsyncADAG", toy_dataset, transport="socket",
                  pipeline=True, compress_commits="int8")
    batched = _train("AsyncADAG", toy_dataset, transport="socket",
                     pipeline=True, compress_commits="int8",
                     recv_batch_depth=8)
    shm = _train("AsyncADAG", toy_dataset, transport="shm", pipeline=True,
                 compress_commits="int8")
    _assert_bit_identical(sock, batched)
    _assert_bit_identical(sock, shm)


def test_recv_batch_depth_matches_plain_socket_bit_identical(toy_dataset):
    """The hub's batched receive path (recvmmsg when available, plain
    nonblocking drains otherwise) parses the same stream — trajectories
    are bit-equal to the unbatched hub."""
    plain = _train("AsyncADAG", toy_dataset, transport="socket",
                   pipeline=True)
    batched = _train("AsyncADAG", toy_dataset, transport="socket",
                     pipeline=True, recv_batch_depth=8)
    _assert_bit_identical(plain, batched)


def test_shm_transport_validation():
    import distkeras_tpu as dk

    tr = dk.AsyncADAG(_mlp_spec(), transport="shm")
    assert tr.transport == "shm"
    with pytest.raises(ValueError, match="recv_batch_depth"):
        dk.AsyncADAG(_mlp_spec(), recv_batch_depth=-1)


def test_pipelined_prefetch_semantics_and_staleness_accounting(toy_dataset):
    """Pipelining's documented semantics (ARCHITECTURE.md "Async
    transport"): the pull for window k+1 is issued BEFORE commit k, so the
    worker trains k+1 from a center missing its own commit k — a genuinely
    staler schedule than serial (the trajectories must differ).  The hub's
    clock staleness, measured from the most recent pull REQUEST on the
    connection, still reads 0 for a lone worker in BOTH modes — it
    undercounts the delta's true base by the prefetch depth, which is why
    exact-staleness consumers (DynSGD scaling studies) use
    ``pipeline=False``."""
    obs.reset()
    obs.enable()
    try:
        piped, _ = _train("AsyncADAG", toy_dataset, transport="inproc",
                          pipeline=True)
        hist = obs.snapshot()["histograms"]["ps_commit_staleness"]
        assert hist["count"] == len(piped.history)
        # every window's prefetch re-arms the connection clock before its
        # commit -> measured 0; only the FINAL window (which has nothing
        # left to prefetch) commits against its window-start pull -> 1,
        # the one commit whose measurement equals the true delta base
        assert hist["sum"] == 1.0 and hist["max"] == 1.0

        obs.reset()
        serial, _ = _train("AsyncADAG", toy_dataset, transport="inproc",
                           pipeline=False)
        hist = obs.snapshot()["histograms"]["ps_commit_staleness"]
        assert hist["count"] == len(serial.history)
        assert hist["sum"] == 0  # serial: every pull reflects every commit
    finally:
        obs.disable()
        obs.reset()
    # same windows, different schedule: the prefetched pulls make the
    # pipelined trajectory diverge from the serial one after window 0
    assert len(piped.history) == len(serial.history)
    assert piped.history[0] == serial.history[0]
    assert piped.history != serial.history
