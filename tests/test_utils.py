"""Unit tests: serialization round-trips and utility math (SURVEY §4.1)."""

import numpy as np
import pytest

from distkeras_tpu import utils
from distkeras_tpu.models.base import Model


def small_mlp():
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2}, input_shape=(8,))


def test_model_serialize_roundtrip():
    model = Model.init(small_mlp(), seed=3)
    blob = model.serialize()
    restored = Model.deserialize(blob)
    assert restored.spec == model.spec
    orig, _ = utils.flatten_weights(model.params)
    back, _ = utils.flatten_weights(restored.params)
    assert len(orig) == len(back)
    for a, b in zip(orig, back):
        np.testing.assert_array_equal(a, b)


def test_serialized_model_predicts_identically():
    model = Model.init(small_mlp(), seed=1)
    x = np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32)
    np.testing.assert_allclose(model.apply(x), Model.deserialize(model.serialize()).apply(x), rtol=1e-6)


def test_uniform_weights_changes_and_bounds():
    model = Model.init(small_mlp(), seed=0)
    new_params = utils.uniform_weights(model.params, seed=7, low=-0.05, high=0.05)
    leaves, _ = utils.flatten_weights(new_params)
    for leaf in leaves:
        assert leaf.min() >= -0.05 and leaf.max() <= 0.05
    old_leaves, _ = utils.flatten_weights(model.params)
    assert any(not np.array_equal(a, b) for a, b in zip(old_leaves, leaves))


def test_shuffle_arrays_consistent_permutation():
    x = np.arange(10)
    y = np.arange(10) * 2
    out = utils.shuffle_arrays({"x": x, "y": y}, seed=1)
    np.testing.assert_array_equal(out["y"], out["x"] * 2)
    assert not np.array_equal(out["x"], x)


def test_shuffle_arrays_rejects_mismatched():
    with pytest.raises(ValueError):
        utils.shuffle_arrays({"x": np.arange(3), "y": np.arange(4)})


class TestEvaluatorSuite:
    """Top-k / confusion / precision-recall-F1 vs hand-computed values."""

    def _ds(self):
        import numpy as _np

        from distkeras_tpu.data.dataset import Dataset as _DS

        logits = _np.array([[3.0, 2.0, 1.0],   # top1=0 top2={0,1}
                            [1.0, 3.0, 2.0],   # top1=1 top2={1,2}
                            [1.0, 2.0, 3.0],   # top1=2 top2={2,1}
                            [2.0, 3.0, 1.0]])  # top1=1 top2={1,0}
        labels = _np.array([0, 2, 2, 1])
        pred_idx = logits.argmax(1)
        return _DS({"prediction": logits.astype(_np.float32),
                    "prediction_index": pred_idx.astype(_np.int64),
                    "label": labels.astype(_np.int64)})

    def test_topk(self):
        from distkeras_tpu.evaluators import TopKAccuracyEvaluator

        ds = self._ds()
        assert TopKAccuracyEvaluator(k=1).evaluate(ds) == pytest.approx(0.75)
        assert TopKAccuracyEvaluator(k=2).evaluate(ds) == pytest.approx(1.0)

    def test_confusion(self):
        import numpy as _np

        from distkeras_tpu.evaluators import ConfusionMatrixEvaluator

        cm = ConfusionMatrixEvaluator(3).evaluate(self._ds())
        want = _np.zeros((3, 3), int)
        want[0, 0] += 1  # true 0 pred 0
        want[2, 1] += 1  # true 2 pred 1
        want[2, 2] += 1  # true 2 pred 2
        want[1, 1] += 1  # true 1 pred 1
        _np.testing.assert_array_equal(cm, want)

    def test_confusion_ignores_out_of_range_indices(self):
        import numpy as _np

        from distkeras_tpu.data.dataset import Dataset as _DS
        from distkeras_tpu.evaluators import ConfusionMatrixEvaluator

        ds = _DS({"prediction_index": _np.array([0, 1, 0, 2]),
                  "label": _np.array([-1, 1, 3, 2])})  # -1 ignore, 3 OOB
        cm = ConfusionMatrixEvaluator(3).evaluate(ds)
        want = _np.zeros((3, 3), int)
        want[1, 1] = 1
        want[2, 2] = 1
        _np.testing.assert_array_equal(cm, want)

    def test_prf1(self):
        from distkeras_tpu.evaluators import PrecisionRecallF1Evaluator

        m = PrecisionRecallF1Evaluator(3).evaluate(self._ds())
        # class 1: tp=1, predicted={1,1} twice -> precision 0.5, true once -> recall 1
        assert m["precision"][1] == pytest.approx(0.5)
        assert m["recall"][1] == pytest.approx(1.0)
        assert m["f1"][1] == pytest.approx(2 / 3)
        # class 0: perfect
        assert m["f1"][0] == pytest.approx(1.0)
        # class 2: tp=1, pred once -> precision 1, true twice -> recall .5
        assert m["f1"][2] == pytest.approx(2 / 3)
        assert m["macro_f1"] == pytest.approx((1.0 + 2 / 3 + 2 / 3) / 3)
