"""Unit tests: serialization round-trips and utility math (SURVEY §4.1)."""

import numpy as np
import pytest

from distkeras_tpu import utils
from distkeras_tpu.models.base import Model
from distkeras_tpu.models.mlp import mnist_mlp_spec


def small_mlp():
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2}, input_shape=(8,))


def test_model_serialize_roundtrip():
    model = Model.init(small_mlp(), seed=3)
    blob = model.serialize()
    restored = Model.deserialize(blob)
    assert restored.spec == model.spec
    orig, _ = utils.flatten_weights(model.params)
    back, _ = utils.flatten_weights(restored.params)
    assert len(orig) == len(back)
    for a, b in zip(orig, back):
        np.testing.assert_array_equal(a, b)


def test_serialized_model_predicts_identically():
    model = Model.init(small_mlp(), seed=1)
    x = np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32)
    np.testing.assert_allclose(model.apply(x), Model.deserialize(model.serialize()).apply(x), rtol=1e-6)


def test_uniform_weights_changes_and_bounds():
    model = Model.init(small_mlp(), seed=0)
    new_params = utils.uniform_weights(model.params, seed=7, low=-0.05, high=0.05)
    leaves, _ = utils.flatten_weights(new_params)
    for leaf in leaves:
        assert leaf.min() >= -0.05 and leaf.max() <= 0.05
    old_leaves, _ = utils.flatten_weights(model.params)
    assert any(not np.array_equal(a, b) for a, b in zip(old_leaves, leaves))


def test_shuffle_arrays_consistent_permutation():
    x = np.arange(10)
    y = np.arange(10) * 2
    out = utils.shuffle_arrays({"x": x, "y": y}, seed=1)
    np.testing.assert_array_equal(out["y"], out["x"] * 2)
    assert not np.array_equal(out["x"], x)


def test_shuffle_arrays_rejects_mismatched():
    with pytest.raises(ValueError):
        utils.shuffle_arrays({"x": np.arange(3), "y": np.arange(4)})
