"""ZeRO-1 sharded optimizer state: must be bit-comparable to replicated DP."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu.models.base import ModelSpec
from distkeras_tpu.ops.losses import get_loss
from distkeras_tpu.parallel.mesh import create_mesh
from distkeras_tpu.parallel.zero import (
    make_zero_train_step, zero_data_sharding, zero_init_state)

R = 8


def _setup(optimizer):
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    loss = get_loss("categorical_crossentropy")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(axis=1) > 0).astype(int)]
    params = jax.tree.map(jnp.asarray, spec.init_params(seed=0))
    return spec, loss, x, y, params


def _replicated_dp_step(spec, loss, optimizer, mesh):
    """Plain data-parallel reference: pmean grads, full optimizer everywhere."""
    apply_fn = spec.apply_fn()

    def fn(params, opt_state, x, y):
        l, grads = jax.value_and_grad(lambda p: loss(apply_fn(p, x), y))(params)
        grads = jax.tree.map(lambda g: lax.pmean(g, "replica"), grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, lax.pmean(l, "replica")

    return jax.jit(jax.shard_map(fn, mesh=mesh,
                                 in_specs=(P(), P(), P("replica"), P("replica")),
                                 out_specs=(P(), P(), P())))


@pytest.mark.parametrize("opt_name,make_opt", [
    ("sgd", lambda: optax.sgd(0.05)),
    ("momentum", lambda: optax.sgd(0.05, momentum=0.9)),
    ("adam", lambda: optax.adam(1e-2)),
])
def test_zero_matches_replicated_dp(opt_name, make_opt):
    mesh = create_mesh(R)
    optimizer = make_opt()
    spec, loss, x, y, params = _setup(optimizer)
    dsh = zero_data_sharding(mesh)
    xd = jax.device_put(jnp.asarray(x), dsh)
    yd = jax.device_put(jnp.asarray(y), dsh)

    ref_step = _replicated_dp_step(spec, loss, optimizer, mesh)
    ref_params = jax.tree.map(jnp.array, params)
    ref_state = optimizer.init(ref_params)

    z_step = make_zero_train_step(spec, loss, optimizer, mesh)
    z_params = jax.device_put(jax.tree.map(jnp.array, params),
                              NamedSharding(mesh, P()))
    z_state = zero_init_state(params, optimizer, mesh)

    for _ in range(5):
        ref_params, ref_state, ref_loss = ref_step(ref_params, ref_state, xd, yd)
        z_params, z_state, z_loss = z_step(z_params, z_state, xd, yd)

    np.testing.assert_allclose(float(z_loss), float(ref_loss), rtol=1e-5)
    for (ka, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(z_params),
            jax.tree_util.tree_leaves_with_path(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6,
                                   err_msg=f"{opt_name}: {jax.tree_util.keystr(ka)}")


def test_zero_state_is_actually_sharded():
    mesh = create_mesh(R)
    optimizer = optax.adam(1e-2)
    spec, loss, x, y, params = _setup(optimizer)
    state = zero_init_state(params, optimizer, mesh)
    total = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    padded = -(-total // R) * R
    # adam: mu and nu vectors are global [padded], each device holds 1/R
    vec_leaves = [l for l in jax.tree.leaves(state) if l.ndim == 1]
    assert len(vec_leaves) == 2
    for leaf in vec_leaves:
        assert leaf.shape == (padded,)
        assert leaf.sharding.spec == P("replica")
        assert leaf.addressable_shards[0].data.shape == (padded // R,)


def test_zero_step_learns():
    mesh = create_mesh(R)
    optimizer = optax.adam(5e-3)
    spec, loss, x, y, params = _setup(optimizer)
    step = make_zero_train_step(spec, loss, optimizer, mesh)
    dsh = zero_data_sharding(mesh)
    xd, yd = jax.device_put(jnp.asarray(x), dsh), jax.device_put(jnp.asarray(y), dsh)
    p = jax.device_put(jax.tree.map(jnp.array, params), NamedSharding(mesh, P()))
    s = zero_init_state(params, optimizer, mesh)
    losses = []
    for _ in range(60):
        p, s, l = step(p, s, xd, yd)
        losses.append(float(l))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.4, (losses[0], losses[-1])
